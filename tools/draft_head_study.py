"""Round-8 trained-draft-head study driver (DECODE.md "Multi-token
decode", the r7 verdict's named next step).

Protocol — post-hoc distillation onto the r7 teacher (the exact
"LayerSkip/Medusa-style, post-hoc" route the r7 verdict named):

1. **Teacher**: train the r7 Markov toy trunk-only, byte-identical to
   ``tools/decode_spec_study.py`` (3000 steps -> loss 1.671 — the
   co-trained alternative was measured and REJECTED: arming the head
   from step 0 perturbs this geometry's late grokking window and the
   teacher lands at loss ~4 instead of ~1.7, which poisons the
   acceptance comparison).
2. **Distill**: for each exit depth L_d ∈ {1, 2} (quarter/half of the
   4-layer toy), attach a fresh gelu-adapter draft head (rank 256 —
   the linear adapter plateaus at α ≈ 0.17; see draft.py) and distill
   it against the FROZEN trunk with the optimizer param-group split
   (``optax.multi_transform``: adam on ``draft_*``, ``set_to_zero``
   on the trunk) — the trunk stays bitwise the r7 teacher, so the
   shared-drafter baseline rows below are the r7 baseline re-measured
   on the same weights.
3. **Measure**: greedy self-speculative acceptance per (k ∈ {2,4,8})
   at b ∈ {1, 8}, trained head AND shared-head baseline. Rows:
   ``kind="acceptance"`` with a ``drafter`` field.
4. **Price**: ``icikit.bench.decode.cost_model_rows`` evaluates the
   acceptance × cost model at every measured α (base-preset b=1
   geometry, the committed 0.703 ms floor) — the same rows
   ``python -m icikit.bench.decode --cost-model --alpha-from <file>``
   reproduces from the records alone — plus one ``kind="verdict"``
   row: α at (k=2, quarter depth, b=1) against the 0.336 break-even
   and the 15%-win threshold.

Usage::

    JAX_PLATFORMS=cpu python tools/draft_head_study.py \
        --json decode_spec_r8.jsonl [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

# runnable as `python tools/draft_head_study.py` from the repo root
# (sys.path[0] is tools/, not the root)
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# the r7 toy geometry (tools/decode_spec_study.py); order-2 Markov
# structure groks late — 3000 steps lands a genuinely predictive
# teacher (loss 1.671, reproduced this round)
TOY = dict(vocab=64, d_model=64, n_heads=2, d_head=32, d_ff=256,
           n_layers=4, max_seq=160, compute_dtype="float32")
DRAFT_RANK = 256
DISTILL_LR = 3e-3


def train_teacher(steps: int):
    """Phase 1: the r7 acceptance-study model, trunk only — byte-
    identical to decode_spec_study.train_toy."""
    import jax
    import jax.numpy as jnp
    import optax

    from icikit.models.transformer import TransformerConfig, init_params
    from icikit.models.transformer.model import (make_model_mesh,
                                                 make_train_step)
    from icikit.models.transformer.train import make_markov_sampler

    cfg = TransformerConfig(**TOY)
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), cfg, mesh)
    sampler = make_markov_sampler(cfg.vocab, seed=0)
    _, step = make_train_step(mesh, cfg, optax.adam(3e-3))
    opt_state = optax.adam(3e-3).init(params)
    loss = None
    for s in range(steps):
        chunk = sampler(s, 16, 64)
        params, opt_state, loss = step(params, opt_state,
                                       jnp.asarray(chunk[:, :-1]),
                                       jnp.asarray(chunk[:, 1:]))
    final = float(np.asarray(loss))
    print(f"teacher trained: {steps} steps, loss {final:.4f}",
          flush=True)
    return mesh, params, sampler, final


def distill_head(mesh, trunk, sampler, exit_layer: int, steps: int):
    """Phase 2: attach a fresh head at ``exit_layer`` and distill it
    against the frozen trunk (param-group split: adam on ``draft_*``,
    zero on everything else — the trunk stays bitwise the teacher)."""
    import jax
    import jax.numpy as jnp
    import optax

    from icikit.models.transformer import TransformerConfig
    from icikit.models.transformer.draft import init_draft_params
    from icikit.models.transformer.model import make_train_step

    cfg = TransformerConfig(**TOY, draft_head=True,
                            draft_layers=exit_layer,
                            draft_rank=DRAFT_RANK, draft_kl=0.5)
    params = dict(trunk)
    params.update(init_draft_params(
        jax.random.fold_in(jax.random.key(0), 7), cfg,
        params["w_out"]))
    tx = optax.multi_transform(
        {"draft": optax.adam(DISTILL_LR), "frozen": optax.set_to_zero()},
        lambda p: {k: ("draft" if k.startswith("draft_") else "frozen")
                   for k in p})
    _, step = make_train_step(mesh, cfg, tx)
    opt_state = tx.init(params)
    metrics = None
    for s in range(steps):
        chunk = sampler(100000 + s, 16, 64)
        params, opt_state, _, metrics = step(params, opt_state,
                                             jnp.asarray(chunk[:, :-1]),
                                             jnp.asarray(chunk[:, 1:]))
    m = {k: float(np.asarray(v)) for k, v in metrics.items()}
    for k in trunk:  # the freeze really froze
        np.testing.assert_array_equal(np.asarray(trunk[k]),
                                      np.asarray(params[k]))
    print(f"head distilled (L_d={exit_layer}, rank={DRAFT_RANK}, "
          f"{steps} steps): draft_loss {m['draft_loss']:.4f}, "
          f"top1_agree {m['draft_top1_agree']:.4f}", flush=True)
    return cfg, params, m


def acceptance_rows(quick: bool) -> list:
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from icikit.models.transformer import speculative_generate

    teach_steps = 120 if quick else 3000
    distill_steps = 120 if quick else 3000
    n_new = 48 if quick else 96
    mesh, trunk, sampler, final_loss = train_teacher(teach_steps)
    rows = []
    for exit_layer in (1, 2):
        cfg, params, tm = distill_head(mesh, trunk, sampler,
                                       exit_layer, distill_steps)
        sh = NamedSharding(mesh, P("dp", None))
        for batch in (1, 8):
            chunk = sampler(2**31 + batch, batch, 8)
            prompt = jax.device_put(jnp.asarray(chunk[:, :8]), sh)
            for k in (2, 4, 8):
                per = {}
                for drafter in ("trained", "shared"):
                    _, st = speculative_generate(
                        params, prompt, mesh, cfg, n_new, k=k,
                        draft_layers=exit_layer, drafter=drafter,
                        return_stats=True)
                    per[drafter] = st
                    rows.append({
                        "kind": "acceptance",
                        "corpus": "markov-order2",
                        "protocol": "r8-posthoc-distill",
                        "drafter": drafter,
                        "train_steps": teach_steps,
                        "distill_steps": distill_steps,
                        "draft_rank": DRAFT_RANK,
                        "teacher_loss": round(final_loss, 4),
                        "train_draft_top1_agree":
                            round(tm["draft_top1_agree"], 4),
                        "n_layers": cfg.n_layers,
                        "batch": batch, "k": k,
                        "draft_layers": exit_layer,
                        "n_new": n_new,
                        "acceptance_rate":
                            round(st["acceptance_rate"], 4),
                        "tokens_per_step":
                            round(st["tokens_per_step"], 4),
                    })
                tr = per["trained"]["acceptance_rate"]
                sh_a = per["shared"]["acceptance_rate"]
                ratio = f" ({tr / sh_a:.1f}x)" if sh_a else ""
                print(f"acceptance b={batch} k={k} L_d={exit_layer}: "
                      f"trained {tr:.3f} vs shared {sh_a:.3f}{ratio}",
                      flush=True)
    return rows


def verdict_row(json_path: str, proj_rows: list) -> dict:
    """The single number the round exists for: trained-head α at
    (k=2, quarter depth, b=1) vs the r7 break-even (0.336) and the
    15%-win threshold."""
    r = [r for r in proj_rows
         if r["k"] == 2 and r["draft_fraction"] == 0.25
         and r["drafter"] == "trained"][0]
    a = r["measured_acceptance"]
    return {
        "kind": "verdict",
        "alpha_source": json_path,
        "alpha_k2_quarter_trained": a,
        "breakeven_alpha": r["breakeven_acceptance"],
        "win15_alpha": r["breakeven_acceptance_15pct"],
        "route_breaks_even": a >= r["breakeven_acceptance"],
        "route_clears_15pct": bool(r["clears_15pct"]),
        "projected_eff_ms_per_token":
            r["projected_eff_ms_per_token"],
        "floor_ms": r["model_floor_ms"],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", dest="json_path",
                    default="decode_spec_r8.jsonl")
    ap.add_argument("--quick", action="store_true",
                    help="CI-sized run (fewer steps/tokens)")
    args = ap.parse_args(argv)

    rows = acceptance_rows(args.quick)
    with open(args.json_path, "a") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")

    # price every measured α through the shared one-command path —
    # these rows are bit-identical to what
    # `python -m icikit.bench.decode --cost-model --alpha-from ...`
    # appends, which is the point: the verdict is reproducible
    from icikit.bench.decode import cost_model_rows
    proj = cost_model_rows(args.json_path, preset="base", batch=1,
                           cache_len=320, alpha_batch=1)
    verdict = verdict_row(args.json_path, proj)
    with open(args.json_path, "a") as f:
        for r in proj + [verdict]:
            f.write(json.dumps(r) + "\n")
    for r in proj:
        print(f"projection k={r['k']} L_d={r['draft_layers']} "
              f"{r['drafter']}: α={r['measured_acceptance']:.3f} -> "
              f"{r['projected_eff_ms_per_token']} ms/tok "
              f"(break-even α={r['breakeven_acceptance']})",
              flush=True)
    print("verdict:", json.dumps(verdict), flush=True)
    print(f"wrote {len(rows) + len(proj) + 1} rows to {args.json_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
