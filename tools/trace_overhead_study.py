#!/usr/bin/env python
"""Round-15 tracing-overhead study: serve_r15.jsonl.

The acceptance bar for request-scoped tracing: at saturated load the
fully-armed observability stack (trace buffer + request trees +
metrics + anomaly watch) must cost <= 5% tokens/s on the serve hot
path vs the disarmed engine, the armed run's exported trace must be
chrome-checker-valid and hold a COMPLETE span tree for every request,
and the clean run must verdict healthy. Both arms land in
serve_r15.jsonl (config-keyed by the ``tracing`` field, median of
``--seeds`` replicas each), plus one summary row carrying the
measured overhead verdict.

Usage::

    JAX_PLATFORMS=cpu python tools/trace_overhead_study.py \\
        --json serve_r15.jsonl --trace /tmp/icikit_r15_trace.json
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from icikit import obs  # noqa: E402
from icikit.obs import chrome, trace_ctx  # noqa: E402
from icikit.bench.serve import run_bench  # noqa: E402

ARM = dict(preset="tiny", rows=4, n_requests=24, rate_rps=1000.0,
           prompt_len=16, new_min=32, new_max=64, block_size=8,
           speculate=3, drafter="suffix", prefill_chunk=16,
           compute_dtype="float32", mode="continuous")


def run_arm(seed: int, armed: bool, trace_path: str | None):
    """One replica: fully armed (trace + metrics + watch) or fully
    disarmed. The armed replica exports and validates its trace and
    asserts one complete request tree per completed request."""
    if not armed:
        (rec,) = run_bench(seed=seed, **ARM)
        return rec
    with obs.session() as s:
        (rec,) = run_bench(seed=seed, watch=True, **ARM)
        events = s.trace.snapshot()
    problems = obs.validate_trace(events)
    assert not problems, problems[:5]
    trees = trace_ctx.request_trees(events)
    # warm-up prompts trace too: at LEAST one tree per timed request
    assert len(trees) >= rec["completed"], (len(trees),
                                            rec["completed"])
    whole = sum(
        1 for evs in trees.values()
        if sum(e["ph"] == "b" for e in evs)
        == sum(e["ph"] == "e" for e in evs)
        and any(e["ph"] == "b" and e["name"] == "serve.req"
                for e in evs))
    assert whole == len(trees), (whole, len(trees))
    assert rec["health"]["healthy"], rec["health"]["alerts"]
    rec["trace_events"] = len(events)
    rec["request_trees"] = len(trees)
    if trace_path:
        chrome.export(trace_path, events)
    return rec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", default="serve_r15.jsonl")
    ap.add_argument("--trace", default="/tmp/icikit_r15_trace.json")
    ap.add_argument("--seeds", type=int, default=4)
    ap.add_argument("--bar", type=float, default=0.05,
                    help="max acceptable relative tokens/s loss")
    args = ap.parse_args(argv)
    rows = []
    tps = {False: [], True: []}
    for seed in range(args.seeds):
        # INTERLEAVED arms, order alternating per seed: host drift
        # (thermal, page cache, allocator state) over a sequential
        # all-A-then-all-B layout reads as fake overhead at this
        # measurement scale (observed ~±5% run-to-run on XLA:CPU)
        order = (False, True) if seed % 2 == 0 else (True, False)
        for armed in order:
            rec = run_arm(seed, armed,
                          args.trace if armed and seed == 0 else None)
            rec["study"] = "trace_overhead_r15"
            rows.append(rec)
            tps[armed].append(rec["tokens_per_s"])
            print(f"armed={armed} seed={seed}: "
                  f"{rec['tokens_per_s']} tok/s", flush=True)
    base = statistics.median(tps[False])
    armed_tps = statistics.median(tps[True])
    overhead = 1.0 - armed_tps / base
    summary = {
        "kind": "serve_trace_overhead",
        "study": "trace_overhead_r15",
        "seeds": args.seeds,
        "arm": {k: v for k, v in ARM.items()},
        "tokens_per_s_disarmed": base,
        "tokens_per_s_armed": armed_tps,
        "overhead_frac": round(overhead, 4),
        "bar_frac": args.bar,
        "within_bar": overhead <= args.bar,
        "note": "CPU-measured; armed = trace buffer + request trees "
                "+ metrics + watch, disarmed = all probes on the "
                "one-global-read fast path",
    }
    rows.append(summary)
    with open(args.json, "a") as f:
        for rec in rows:
            f.write(json.dumps(rec) + "\n")
    print(f"overhead: {overhead:+.2%} (bar {args.bar:.0%}) -> "
          f"{'OK' if summary['within_bar'] else 'OVER BAR'}")
    return 0 if summary["within_bar"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
