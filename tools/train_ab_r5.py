"""Round-5 train-ceiling structural A/Bs (VERDICT r4 #1) at the base
preset: recompute vs saved head x fp32 vs bf16 moments, b=8 and b=16,
interleaved within one session so every variant sees the same tunnel
mood. Appends records to train_ab_r5.jsonl.
"""

import json
import sys

from icikit.bench.train import run_bench


def main():
    batches = [int(b) for b in (sys.argv[1:] or ["8"])]
    variants = [
        dict(head="recompute", optimizer="fused"),        # baseline
        dict(head="saved", optimizer="fused"),            # route (b)
        dict(head="recompute", optimizer="fused-bf16nu"),  # route (a)
        dict(head="recompute", optimizer="fused-bf16mom"),
        dict(head="saved", optimizer="fused-bf16mom"),    # combined
    ]
    for batch in batches:
        for v in variants:
            rec = run_bench("base", 1, 1, 1, batch, steps=10, warmup=3,
                            windows=3, **v)
            rec["ab"] = v
            print(json.dumps(rec), flush=True)
            with open("train_ab_r5.jsonl", "a") as f:
                f.write(json.dumps(rec) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
