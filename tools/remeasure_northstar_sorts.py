"""Re-measure NORTHSTAR's 2^28 quicksort/sample/sample_bitonic rows
under the median-of-windows(+escalation) headline protocol — the three
rows VERDICT r4 flagged as pre-protocol residue. Appends kind:sort
records to northstar.jsonl; re-render with
`python -m icikit.bench.northstar --regen northstar.jsonl --out NORTHSTAR.md`.
"""

import dataclasses
import json
import sys

from icikit.bench.sort import sweep_sorts
from icikit.utils.mesh import make_mesh


def main():
    mesh = make_mesh()
    algs = ("quicksort", "sample", "sample_bitonic")
    recs = sweep_sorts(mesh, (1 << 28,), algorithms=algs, runs=4,
                       warmup=1, windows=3)
    with open("northstar.jsonl", "a") as f:
        for r in recs:
            f.write(json.dumps({**dataclasses.asdict(r),
                                "kind": "sort"}) + "\n")
    for r in recs:
        print(r.algorithm, f"{r.keys_per_s / 1e6:.1f} Mkeys/s",
              f"median {r.mean_s * 1e3:.1f} ms",
              f"spread [{r.min_s * 1e3:.1f}, {r.max_s * 1e3:.1f}]",
              r.session_quality, f"errors={r.errors}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
