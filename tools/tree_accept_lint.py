"""`make check` lint (round 14): ONE accept implementation.

The token-tree verify path's exactness argument leans on the primary
chain being accepted by the *existing* chain rule — `_accept_tree`
must run `_accept_window` verbatim (so the b=1 tree path and the
chain path cannot drift apart semantically), and nothing else in the
tree may re-implement either accept. Mechanically enforced:

1. `_accept_window` and `_accept_tree` are each defined exactly once,
   in `icikit/models/transformer/speculative.py`;
2. `_accept_tree`'s body CALLS `_accept_window` (the primary chain
   goes through the one rule, not a fork of its semantics);
3. the serving engine defines no accept of its own — it imports both
   from speculative.py (the engine-vs-generate identity contract
   hangs on the shared rule).

Run: JAX_PLATFORMS=cpu python tools/tree_accept_lint.py
"""

from __future__ import annotations

import ast
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SPEC = os.path.join(ROOT, "icikit", "models", "transformer",
                    "speculative.py")


def fail(msg: str) -> None:
    print(f"tree-accept lint FAILED: {msg}")
    sys.exit(1)


def defs_in(path: str, names: set[str]) -> dict[str, ast.FunctionDef]:
    with open(path) as f:
        tree = ast.parse(f.read(), path)
    out: dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name in names:
            if node.name in out:
                fail(f"{node.name} defined more than once in {path}")
            out[node.name] = node
    return out


def calls_in(fn: ast.FunctionDef) -> set[str]:
    names = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name):
                names.add(f.id)
            elif isinstance(f, ast.Attribute):
                names.add(f.attr)
    return names


def main() -> int:
    accept_names = {"_accept_window", "_accept_tree"}
    spec_defs = defs_in(SPEC, accept_names)
    for name in accept_names:
        if name not in spec_defs:
            fail(f"{name} not defined in {SPEC}")
    if "_accept_window" not in calls_in(spec_defs["_accept_tree"]):
        fail("_accept_tree does not call _accept_window — the "
             "primary chain must run the ONE chain accept rule, "
             "not a re-implementation")
    # no second definition anywhere else in the package
    for dirpath, _, files in os.walk(os.path.join(ROOT, "icikit")):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            if os.path.abspath(path) == os.path.abspath(SPEC):
                continue
            with open(path) as f:
                src = f.read()
            if ("def _accept_window" in src
                    or "def _accept_tree" in src):
                fail(f"{path} defines its own accept — import the "
                     "shared rule from speculative.py instead")
    # the engine consumes the shared rule, not a local fork
    eng = os.path.join(ROOT, "icikit", "serve", "engine.py")
    with open(eng) as f:
        esrc = f.read()
    for name in accept_names:
        if name not in esrc:
            fail(f"{eng} does not reference {name} — the engine's "
                 "verify windows must run the shared accept")
    print("tree-accept lint OK: one accept implementation "
          "(_accept_tree wraps _accept_window; engine imports both)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
