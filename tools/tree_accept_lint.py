"""Thin shim: this lint is now the ``tree-accept`` rule of the
unified analysis framework (``icikit.analysis``, docs/ANALYSIS.md) —
ONE speculative accept implementation (``_accept_tree`` runs
``_accept_window`` verbatim; the engine imports both). The AST check
lives in ``icikit.analysis.rules.tree_accept``; ``make check`` runs
the whole suite as ``python -m icikit.analysis --gate``.

Run standalone: ``python tools/tree_accept_lint.py``.
"""

from __future__ import annotations

import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from icikit.analysis.rules.tree_accept import (  # noqa: E402,F401
    ACCEPT_NAMES,
    check_tree_accept,
)

RULE = "tree-accept"


def main() -> int:
    from icikit.analysis import shim_main
    return shim_main(RULE, "tree-accept lint OK (via icikit."
                           "analysis): one accept implementation "
                           "(_accept_tree wraps _accept_window; "
                           "engine imports both)")


if __name__ == "__main__":
    sys.exit(main())
