# Top-level targets (the reference's per-sub-repo Makefile template,
# */Makefile:29-62, collapsed into one ops entry point; the native
# runtime keeps the wildcard-compile discipline in icikit/native/Makefile).

PY ?= python

.PHONY: test test-fast chaos bench native clean sweep scaling northstar

test:
	$(PY) -m pytest tests/ -q

test-fast:
	$(PY) -m pytest tests/ -q -m "not slow"

chaos:
	$(PY) -m pytest tests/ -q -m chaos

bench:
	$(PY) bench.py

native:
	$(MAKE) -C icikit/native

sweep:
	$(PY) -m icikit.bench.run --family allgather

scaling:
	$(PY) -m icikit.bench.scaling

northstar:
	$(PY) -m icikit.bench.northstar --out NORTHSTAR.md --json northstar.jsonl

clean:
	$(MAKE) -C icikit/native clean
	find . -name __pycache__ -type d -exec rm -rf {} +
