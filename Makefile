# Top-level targets (the reference's per-sub-repo Makefile template,
# */Makefile:29-62, collapsed into one ops entry point; the native
# runtime keeps the wildcard-compile discipline in icikit/native/Makefile).

PY ?= python

.PHONY: test test-fast chaos bench native clean sweep scaling northstar \
	trace-demo check analysis-smoke decode-smoke draft-smoke \
	serve-smoke quant-smoke obs-smoke fleet-smoke fleet-ha-smoke \
	fleet-obs-smoke fleet-route-smoke

test:
	$(PY) -m pytest tests/ -q

test-fast:
	$(PY) -m pytest tests/ -q -m "not slow"

chaos:
	$(PY) -m pytest tests/ -q -m chaos

# end-to-end observability self-check: tiny train + healed solve +
# collective sweep under an armed obs session (validates the Chrome
# trace, the metrics keys, and the disabled-path overhead), then the
# same tiny train env-armed via ICIKIT_OBS with the exported trace
# checked by the structural validator
trace-demo:
	JAX_PLATFORMS=cpu $(PY) -m icikit.obs.demo \
		--trace /tmp/icikit_trace.json --metrics /tmp/icikit_obs_metrics.json
	JAX_PLATFORMS=cpu \
	ICIKIT_OBS="trace=/tmp/icikit_trace_env.json;metrics=/tmp/icikit_obs_metrics_env.json;jsonl=stderr" \
	$(PY) -m icikit.models.transformer.train --steps 4 --batch 4 \
		--vocab 32 --d-model 32 --n-heads 2 --d-head 8 --d-ff 64 \
		--n-layers 1 --seq 16 --compute-dtype float32 --log-every 2 \
		--sample-tokens 0 > /dev/null
	$(PY) -m icikit.obs.check /tmp/icikit_trace_env.json

# static analysis: ONE entry point for the whole invariant suite
# (docs/ANALYSIS.md) — the six former lint scripts, the two former
# grep lints, and the host-sync + lock-discipline hot-path analyses,
# all as rules of icikit.analysis. --gate fails on any unbaselined
# finding; --self-check proves each seedable rule still catches its
# planted violation (a gate that cannot fail is not a gate); --budget
# asserts the suite stays cheap enough to run on every PR. The bench
# regression self-check rides along: it gates measured records, not
# source invariants, so it is not an analysis rule.
check:
	JAX_PLATFORMS=cpu $(PY) -m icikit.analysis --gate --self-check \
		--budget 30
	$(PY) tools/bench_regress.py --self-check serve_r12.jsonl \
		serve_r15.jsonl serve_r16.jsonl serve_fleet_r17.jsonl \
		serve_fleet_ha_r18.jsonl serve_fleet_obs_r19.jsonl \
		serve_fleet_route_r20.jsonl decode_spec_r14.jsonl \
		--verdict /tmp/icikit_bench_regress.json

# machine-readable analysis output: the --json shape the tooling
# consumes (report path, rule list, per-finding records with their
# baselined flag) — exercised here so a shape change fails CI, not a
# downstream consumer
analysis-smoke:
	JAX_PLATFORMS=cpu $(PY) -m icikit.analysis \
		--json /tmp/icikit_analysis.json
	$(PY) -c "import json; d = json.load(open('/tmp/icikit_analysis.json')); \
	assert d['version'] == 1 and len(d['rules']) >= 9, d['rules']; \
	assert all({'rule','path','line','msg','baselined'} == set(f) \
	    for f in d['findings']), 'finding shape drifted'; \
	assert d['counts']['unbaselined'] == 0, d['counts']; \
	print('analysis-smoke OK:', len(d['rules']), 'rules,', \
	    d['counts']['findings'], 'findings, json shape stable')"

# request-scoped tracing + anomaly watch, end to end: a tiny Poisson
# serve session with the trace AND the watch armed — the exported
# trace must pass the structural checker (async request trees
# included), hold at least one COMPLETE per-request span tree, and the
# clean run must verdict healthy with zero obs.alert events
obs-smoke:
	rm -f /tmp/icikit_obs_smoke.jsonl
	JAX_PLATFORMS=cpu \
	ICIKIT_OBS="trace=/tmp/icikit_obs_smoke_trace.json;metrics=/tmp/icikit_obs_smoke_metrics.json;jsonl=off" \
	$(PY) -m icikit.bench.serve --preset tiny --rows 2 --requests 8 \
		--rate 50 --prompt 16 --new-min 4 --new-max 8 --block-size 4 \
		--prefill-chunk 8 --speculate 3 --mode continuous --seed 0 \
		--watch --json /tmp/icikit_obs_smoke.jsonl > /dev/null
	$(PY) -m icikit.obs.check /tmp/icikit_obs_smoke_trace.json
	$(PY) tools/obs_smoke_check.py /tmp/icikit_obs_smoke_trace.json \
		/tmp/icikit_obs_smoke.jsonl

# multi-token decode smoke: a tiny CPU speculative decode under an
# armed obs session — the acceptance counters/spans must flow and the
# exported Chrome trace must pass the structural validator (keeps the
# weights-stationary decode path collected alongside its tier-1 tests)
decode-smoke:
	JAX_PLATFORMS=cpu \
	ICIKIT_OBS="trace=/tmp/icikit_decode_trace.json;metrics=/tmp/icikit_decode_metrics.json;jsonl=off" \
	$(PY) -m icikit.bench.decode --preset tiny --batch 2 --prompt 8 \
		--new 12 --speculate 3 --draft-layers 1 --runs 1 > /dev/null
	$(PY) -m icikit.obs.check /tmp/icikit_decode_trace.json
	@grep -q "decode.spec.draft_accepted" /tmp/icikit_decode_metrics.json \
		&& echo "decode-smoke OK: trace valid, acceptance counters present"
	JAX_PLATFORMS=cpu \
	ICIKIT_OBS="trace=/tmp/icikit_tree_trace.json;metrics=/tmp/icikit_tree_metrics.json;jsonl=off" \
	$(PY) -m icikit.bench.decode --preset tiny --batch 2 --prompt 8 \
		--new 12 --speculate 3 --draft-layers 1 --tree-branch 2 \
		--drafter ngram --runs 1 > /dev/null
	$(PY) -m icikit.obs.check /tmp/icikit_tree_trace.json
	@grep -q "decode.spec.tree.draft_accepted" /tmp/icikit_tree_metrics.json \
		&& echo "decode-smoke OK: tree leg trace valid, tree acceptance counters present"

# trained-draft-head smoke: a tiny self-distillation run (draft head
# armed, per-step draft.loss/draft.top1_agree on the obs bus) that
# finishes with a greedy speculative decode using the head it just
# trained (--draft-sample), all under an armed obs session — the
# exported trace must pass the structural validator and the metrics
# snapshot must hold both the distill and the acceptance counters
draft-smoke:
	JAX_PLATFORMS=cpu \
	ICIKIT_OBS="trace=/tmp/icikit_draft_trace.json;metrics=/tmp/icikit_draft_metrics.json;jsonl=off" \
	$(PY) -m icikit.models.transformer.train --steps 8 --batch 4 \
		--vocab 32 --d-model 32 --n-heads 2 --d-head 8 --d-ff 64 \
		--n-layers 2 --seq 32 --compute-dtype float32 --log-every 4 \
		--lr 1e-2 --draft-head --draft-layers 1 --draft-sample 8 \
		--sample-tokens 0 > /dev/null
	$(PY) -m icikit.obs.check /tmp/icikit_draft_trace.json
	@grep -q "draft.loss" /tmp/icikit_draft_metrics.json && \
		grep -q "decode.spec.draft_accepted" /tmp/icikit_draft_metrics.json && \
		echo "draft-smoke OK: trace valid, distill + trained-drafter metrics present"

# quantized-decode smoke: a tiny int8 generate (decode-bench row, the
# acceptance counters still flow) and an int8 serving step, both under
# an armed obs session with the exported trace structurally validated
# — keeps the int8 path (weights + KV + engine arenas) exercised
# end-to-end alongside its tier-1 tests
quant-smoke:
	JAX_PLATFORMS=cpu \
	ICIKIT_OBS="trace=/tmp/icikit_quant_trace.json;metrics=/tmp/icikit_quant_metrics.json;jsonl=off" \
	$(PY) -m icikit.bench.decode --preset tiny --batch 2 --prompt 8 \
		--new 12 --decode-quant int8 --runs 1 > /dev/null
	$(PY) -m icikit.obs.check /tmp/icikit_quant_trace.json
	JAX_PLATFORMS=cpu \
	ICIKIT_OBS="trace=/tmp/icikit_quant_serve_trace.json;metrics=/tmp/icikit_quant_serve_metrics.json;jsonl=off" \
	$(PY) -m icikit.bench.serve --preset tiny --rows 2 --requests 4 \
		--rate 50 --prompt 8 --new-min 4 --new-max 8 --block-size 4 \
		--decode-quant int8 --mode continuous --seed 0 > /dev/null
	$(PY) -m icikit.obs.check /tmp/icikit_quant_serve_trace.json
	@grep -q "serve.ttft_ms" /tmp/icikit_quant_serve_metrics.json && \
		echo "quant-smoke OK: int8 generate + serve traces valid"

# continuous-batching serving smoke: a tiny Poisson-arrival engine run
# under an armed obs session — the serve.request spans must pass the
# structural trace validator and the SLO histograms must land in the
# metrics snapshot — then the KV-page corruption drill end-to-end via
# ICIKIT_CHAOS (the victim request fails its integrity verify, retries
# on fresh blocks, the run completes, and --expect-chaos asserts the
# probe actually fired)
serve-smoke:
	JAX_PLATFORMS=cpu \
	ICIKIT_OBS="trace=/tmp/icikit_serve_trace.json;metrics=/tmp/icikit_serve_metrics.json;jsonl=off" \
	$(PY) -m icikit.bench.serve --preset tiny --rows 2 --requests 6 \
		--rate 50 --prompt 8 --new-min 4 --new-max 8 --block-size 4 \
		--mode continuous --seed 0 > /dev/null
	$(PY) -m icikit.obs.check /tmp/icikit_serve_trace.json
	@grep -q "serve.ttft_ms" /tmp/icikit_serve_metrics.json && \
		grep -q "serve.tpot_ms" /tmp/icikit_serve_metrics.json && \
		echo "serve-smoke OK: trace valid, SLO histograms present"
	JAX_PLATFORMS=cpu ICIKIT_CHAOS="seed=0;corrupt:serve.kv.page=@0" \
	$(PY) -m icikit.bench.serve --preset tiny --rows 2 --requests 4 \
		--rate 100 --prompt 8 --new-min 4 --new-max 8 --block-size 4 \
		--integrity pages --mode continuous --seed 0 \
		--expect-chaos corrupt:serve.kv.page > /dev/null
	@echo "serve-smoke chaos OK: KV-page drill fired and the run completed"
	JAX_PLATFORMS=cpu \
	ICIKIT_OBS="trace=/tmp/icikit_serve_prefix_trace.json;metrics=/tmp/icikit_serve_prefix_metrics.json;jsonl=off" \
	$(PY) -m icikit.bench.serve --preset tiny --rows 2 --requests 6 \
		--rate 50 --prompt 16 --prefix 12 --new-min 4 --new-max 8 \
		--block-size 4 --prefill-chunk 8 --compute-dtype float32 \
		--mode continuous --seed 0 --verify-identity > /dev/null
	$(PY) -m icikit.obs.check /tmp/icikit_serve_prefix_trace.json
	@grep -q '"serve.prefix.hits"' /tmp/icikit_serve_prefix_metrics.json && \
		grep -q '"serve.prefix.hit_tokens"' /tmp/icikit_serve_prefix_metrics.json && \
		echo "serve-smoke prefix OK: shared-prefix trace valid, cache-hit admissions on the bus"
	JAX_PLATFORMS=cpu \
	ICIKIT_OBS="trace=/tmp/icikit_serve_sampled_trace.json;metrics=/tmp/icikit_serve_sampled_metrics.json;jsonl=off" \
	$(PY) -m icikit.bench.serve --preset tiny --rows 2 --requests 6 \
		--rate 2000 --prompt 16 --new-min 4 --new-max 8 --block-size 4 \
		--prefill-chunk 4 --distinct 1 --temperature 0.7 --top-p 0.9 \
		--seed-per-request --compute-dtype float32 --mode continuous \
		--seed 0 --verify-identity > /dev/null
	$(PY) -m icikit.obs.check /tmp/icikit_serve_sampled_trace.json
	@grep -q '"serve.prefix.inflight_hits"' /tmp/icikit_serve_sampled_metrics.json && \
		grep -q '"serve.ttft_ms"' /tmp/icikit_serve_sampled_metrics.json && \
		echo "serve-smoke sampled OK: sampled duplicate-prompt trace valid, in-flight dedup waiters on the bus"
	rm -rf /tmp/icikit_smoke_store
	JAX_PLATFORMS=cpu \
	ICIKIT_OBS="trace=/tmp/icikit_serve_spill_trace.json;metrics=/tmp/icikit_serve_spill_metrics.json;jsonl=off" \
	$(PY) -m icikit.bench.serve --preset tiny --rows 2 --requests 14 \
		--rate 200 --prompt 16 --prefix 12 --tenants 4 --zipf 0.0 \
		--new-min 4 --new-max 8 --block-size 4 --blocks 13 \
		--host-blocks 16 --prefill-chunk 8 --compute-dtype float32 \
		--mode continuous --seed 0 \
		--store-dir /tmp/icikit_smoke_store --verify-identity > /dev/null
	$(PY) -m icikit.obs.check /tmp/icikit_serve_spill_trace.json
	@grep -q '"serve.prefix.spill_hits"' /tmp/icikit_serve_spill_metrics.json && \
		grep -q '"serve.prefix.restores"' /tmp/icikit_serve_spill_metrics.json && \
		echo "serve-smoke spill OK: tiny-pool Zipf traffic spilled and swapped back in, identity-audited"
	JAX_PLATFORMS=cpu \
	ICIKIT_OBS="trace=/tmp/icikit_serve_rewarm_trace.json;metrics=/tmp/icikit_serve_rewarm_metrics.json;jsonl=off" \
	$(PY) -m icikit.bench.serve --preset tiny --rows 2 --requests 14 \
		--rate 200 --prompt 16 --prefix 12 --tenants 4 --zipf 0.0 \
		--new-min 4 --new-max 8 --block-size 4 --blocks 13 \
		--host-blocks 16 --prefill-chunk 8 --compute-dtype float32 \
		--mode continuous --seed 0 \
		--store-dir /tmp/icikit_smoke_store --rewarm \
		--verify-identity > /dev/null
	$(PY) -m icikit.obs.check /tmp/icikit_serve_rewarm_trace.json
	@grep -q '"serve.store.rewarm_blocks"' /tmp/icikit_serve_rewarm_metrics.json && \
		echo "serve-smoke rewarm OK: restarted engine re-warmed the pending prompts from the persisted store, identity-audited"

# multi-engine fleet smoke: a 2-engine disaggregated Poisson run
# (prefill + decode worker PROCESSES behind the coordinator) under an
# armed obs session — the coordinator-side trace must pass the
# structural checker and the metrics snapshot must show the fleet
# alive-gauge and at least one cross-engine KV migration on the bus;
# then the kill-one-engine drill: one worker dies mid-decode at its
# 6th lease renewal (die:fleet.engine.die), the coordinator reissues
# its leases, and the run must still complete every request
# identity-clean with >= 1 reissue observed
fleet-smoke:
	JAX_PLATFORMS=cpu \
	ICIKIT_OBS="trace=/tmp/icikit_fleet_trace.json;metrics=/tmp/icikit_fleet_metrics.json;jsonl=off" \
	$(PY) -m icikit.bench.fleet --engines 2 --roles disagg \
		--requests 8 --rate 20 --prompt 12 --new-min 4 --new-max 8 \
		--prefix 8 --verify-identity --seed 0 > /dev/null
	$(PY) -m icikit.obs.check /tmp/icikit_fleet_trace.json
	@grep -q '"fleet.engines.alive"' /tmp/icikit_fleet_metrics.json && \
		grep -q '"fleet.kv.migrations"' /tmp/icikit_fleet_metrics.json && \
		grep -q '"fleet.handoffs"' /tmp/icikit_fleet_metrics.json && \
		echo "fleet-smoke OK: trace valid, engines alive + cross-engine migration on the bus"
	JAX_PLATFORMS=cpu $(PY) -m icikit.bench.fleet --engines 2 \
		--requests 8 --rate 50 --prompt 12 --new-min 4 --new-max 8 \
		--lease 2 --kill 1:6 --expect-reissue --verify-identity \
		--seed 0 > /dev/null
	@echo "fleet-smoke kill-drill OK: engine died mid-decode, leases reissued, all requests completed bitwise"

# the r19 fleet obs plane: 2-engine disaggregated run with the
# telemetry plane armed end-to-end — workers forward bus events /
# metrics / trace deltas to the coordinator-side collector, which
# must yield ONE merged checker-valid trace containing at least one
# async request tree spanning both engine processes
# (prefill -> handoff -> decode), with zero telemetry loss
# (dropped == corrupt_frames == lost_batches == 0) and a healthy
# aggregated-watch verdict
fleet-obs-smoke:
	JAX_PLATFORMS=cpu $(PY) -m icikit.bench.fleet --engines 2 \
		--roles disagg --requests 8 --rate 20 --prompt 12 \
		--new-min 4 --new-max 8 --prefix 8 --verify-identity \
		--seed 0 --fleet-obs \
		--obs-out /tmp/icikit_fleet_obs_trace.json \
		--json /tmp/icikit_fleet_obs_rec.jsonl \
		> /tmp/icikit_fleet_obs_out.txt
	$(PY) -m icikit.obs.check /tmp/icikit_fleet_obs_trace.json
	@$(PY) -c "import json; \
		line = [l for l in open('/tmp/icikit_fleet_obs_out.txt') \
		        if l.startswith('FLEET_OBS ')][-1]; \
		r = json.loads(line[len('FLEET_OBS '):]); \
		assert r['dropped'] == 0, f'telemetry dropped: {r}'; \
		assert r['corrupt_frames'] == 0, f'corrupt frames: {r}'; \
		assert r['lost_batches'] == 0, f'lost batches: {r}'; \
		assert r['cross_process_trees'] >= 1, f'no cross-process tree: {r}'; \
		assert r['healthy'], f'unhealthy verdict: {r}'; \
		print('fleet-obs-smoke OK: merged trace checker-valid,', \
		      r['cross_process_trees'], 'cross-process trees,', \
		      r['batches'], 'batches, zero telemetry loss')"

# the r20 cache-aware dispatch plane: a 3-engine disaggregated Zipf
# multi-tenant run (1 prefill + 2 decode) with prefix-locality claim
# routing and the host-RAM bridge tier armed — the coordinator-side
# trace must pass the structural checker and the metrics snapshot
# must show steered claims (the router actually re-ordered who won a
# decode lease) and RAM-tier bridge hits (migrated KV served from
# host memory, not the .npz disk tier), every completion
# identity-audited
fleet-route-smoke:
	JAX_PLATFORMS=cpu \
	ICIKIT_OBS="trace=/tmp/icikit_fleet_route_trace.json;metrics=/tmp/icikit_fleet_route_metrics.json;jsonl=off" \
	$(PY) -m icikit.bench.fleet --engines 3 --roles disagg \
		--requests 16 --rate 12 --prompt 24 --prefix 20 \
		--tenants 4 --zipf 1.2 --new-min 4 --new-max 8 --route \
		--verify-identity --seed 0 > /dev/null
	$(PY) -m icikit.obs.check /tmp/icikit_fleet_route_trace.json
	@grep -q '"fleet.route.steered"' /tmp/icikit_fleet_route_metrics.json && \
		grep -q '"fleet.bridge.ram_hits"' /tmp/icikit_fleet_route_metrics.json && \
		echo "fleet-route-smoke OK: trace valid, steered claims + RAM-tier bridge hits on the bus"

# the r18 HA drill: 2 engines + 1 warm standby, the leader SIGKILLed
# mid-decode — the standby must promote inside 2x the lease timeout
# (asserted by the bench), every completion stays bitwise vs
# single-request decode, and the failover lands as fleet.leader.*
# events on the obs bus
fleet-ha-smoke:
	JAX_PLATFORMS=cpu $(PY) -m icikit.bench.fleet --ha --engines 2 \
		--standbys 1 --requests 8 --rate 8 --prompt 8 \
		--new-min 6 --new-max 10 --rows 2 --verify-identity \
		--lease 5 --lease-timeout 1.5 --seed 0 > /dev/null
	@echo "fleet-ha-smoke OK: leader killed mid-decode, standby promoted inside the failover bound, completions bitwise"

bench:
	$(PY) bench.py

native:
	$(MAKE) -C icikit/native

sweep:
	$(PY) -m icikit.bench.run --family allgather

scaling:
	$(PY) -m icikit.bench.scaling

northstar:
	$(PY) -m icikit.bench.northstar --out NORTHSTAR.md --json northstar.jsonl

clean:
	$(MAKE) -C icikit/native clean
	find . -name __pycache__ -type d -exec rm -rf {} +
