"""Tests for the peg-solitaire DLB subsystem (SURVEY.md C21-C25).

The reference's oracle was the printed "found N solutions" count against
known datasets (SURVEY.md §4.4); here that becomes: JAX solver vs
pure-Python DFS oracle (exact solved/moves/steps parity), replay
validation of every emitted solution, golden solution counts on
deterministic generated datasets, and scheduler-equivalence checks
(static and dynamic must agree with each other and the oracle).
"""

import numpy as np
import pytest

import jax

from icikit.models.solitaire import (
    BoardBatch,
    generate_dataset,
    load_dataset,
    parse_board,
    pretty_board,
    render_board,
    render_solution,
    replay_moves,
    save_dataset,
    solve_batch,
    solve_dynamic,
    solve_one_py,
    solve_static,
)
from icikit.models.solitaire.game import (
    EXHAUSTED,
    SOLVED,
    STEP_LIMIT,
    apply_move,
)
from icikit.models.solitaire.scheduler import write_solutions

# The reference's shipped fixtures and their golden solution counts —
# the "found N solutions" oracle (main.cc:135), computed by the native
# solver (which preserves the reference's (i,j,dir) move-enumeration
# order) and pinned here. SURVEY.md §4.4 / VERDICT r1 missing #1.
_REF_DATA = "/root/reference/Dynamic-Load-Balancing/Data"
GOLDEN_COUNTS = {
    "easy_sample.dat": (1000, 32),
    "hard_sample.dat": (1000, 115),
    "big_set/easy_sample.dat.gz": (20000, 1116),
    "big_set/medium_sample.dat.gz": (20000, 1742),
    "big_set/hard_sample.dat.gz": (20000, 27),
}


def _ref_fixture(name):
    import os
    path = os.path.join(_REF_DATA, name)
    if not os.path.exists(path):
        pytest.skip(f"reference fixture {name} not present")
    return load_dataset(path)


# big_set/hard (34 s of native DFS) runs slow-marked so all five
# reference fixtures are asserted by the suite; the other four run on
# every test pass.
@pytest.mark.parametrize("name", [
    "easy_sample.dat", "hard_sample.dat",
    "big_set/easy_sample.dat.gz",
    "big_set/medium_sample.dat.gz",
    pytest.param("big_set/hard_sample.dat.gz", marks=pytest.mark.slow),
])
def test_reference_fixture_golden_counts(name):
    """Native solver over the reference's shipped fixtures reproduces
    the committed golden counts (the reference's only real test
    fixtures)."""
    from icikit.models.solitaire.scheduler import solve_host
    batch = _ref_fixture(name)
    n_games, golden = GOLDEN_COUNTS[name]
    assert len(batch) == n_games  # count header honored (Data/*.dat:1)
    rep = solve_host(batch)
    assert int(rep.solved.sum()) == golden


def test_reference_fixture_jax_agrees_with_native():
    """The JAX while_loop solver and both schedulers agree with the
    native DFS per-board on a slice of the reference's easy fixture
    (deep-search boards are the host backend's job — see FIXTURES.md;
    grade-mixed JAX-vs-native agreement is pinned separately on
    generated datasets below)."""
    from icikit.models.solitaire.scheduler import solve_host
    batch = _ref_fixture("easy_sample.dat")[:96]
    host = solve_host(batch)
    static = solve_static(batch)
    np.testing.assert_array_equal(static.solved, host.solved)
    dynamic = solve_dynamic(batch)
    np.testing.assert_array_equal(dynamic.solved, host.solved)


def test_dynamic_beats_static_imbalance_on_skewed_data():
    """The point of the reference sub-repo (Dynamic-Load-Balancing/
    README.md:5): under variable DFS cost, the pull model spreads the
    expensive boards while a static contiguous split concentrates them.

    Schedule quality is evaluated deterministically: exact per-board
    DFS costs (node counts from a real solve) replayed through
    simulate_schedule's virtual clock — on a host with fewer cores
    than workers, live-thread telemetry measures the OS scheduler, not
    the algorithm. The live dynamic run still pins result agreement."""
    from icikit.models.solitaire.dataset import generate_skewed_dataset
    from icikit.models.solitaire.scheduler import simulate_schedule
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device simulated mesh")
    ds = generate_skewed_dataset(256, seed=3, hard_fraction=0.25)
    static = solve_static(ds, max_steps=200_000)
    dynamic = solve_dynamic(ds, chunk_size=4, max_steps=200_000)
    # same work, same answers, full coverage
    np.testing.assert_array_equal(static.solved, dynamic.solved)
    assert sum(dynamic.per_worker_games) == len(ds)

    def imb(per):
        per = np.asarray(per, np.float64)
        return per.max() / per.mean()

    st = simulate_schedule(static.steps, p=8, strategy="static")
    dy = simulate_schedule(static.steps, p=8, strategy="dynamic",
                           chunk_size=4)
    # every hard board sits in the last static slice: imbalance -> p
    assert imb(st) > 3.0, st
    # the pull model spreads the 16 hard chunks over all 8 workers
    # (floor set by the costliest single chunk, ~1.7 here)
    assert imb(dy) < imb(st) / 2, (st, dy)
    assert imb(dy) < 2.0, dy
    # modeled critical path (= ideal wall time) shrinks accordingly
    assert max(dy) < max(st) / 2, (st, dy)


def test_dynamic_guided_pull_single_device_dispatch_count():
    """Guided pulls amortize dispatches: a 1-worker drain of a c-chunk
    queue takes O(log c) pulls, not c (ROADMAP r1 item 6 — the p=1
    overhead that made dynamic 6.8x slower than static in the r1
    northstar)."""
    from icikit.models.solitaire import scheduler as sched
    ds = generate_dataset(256, "easy", seed=5)  # 32 chunks of 8
    pulls = []
    orig = sched.solve_batch

    def counting(pg, pl, max_steps=2_000_000_000):
        pulls.append(int(pg.shape[0]))
        return orig(pg, pl, max_steps)

    sched.solve_batch, _saved = counting, orig
    try:
        rep = sched.solve_dynamic(ds, devices=jax.devices()[:1])
    finally:
        sched.solve_batch = _saved
    assert rep.n_solutions == solve_static(ds).n_solutions
    assert len(pulls) == 32   # every chunk still solved chunk-shaped
    assert all(c == 8 for c in pulls)  # one compiled shape throughout
    # 32 chunks, one worker: guided pulls of 16, 8, 4, 2, 1, 1 = 6
    # host barriers instead of 32
    assert rep.n_pulls <= 8, rep.n_pulls
    assert rep.per_worker_games == [256]


# ---------------------------------------------------------------------------
# Board encoding

def test_parse_render_roundtrip():
    s = "1102211222112221122212222"
    pegs, playable = parse_board(s)
    assert render_board(pegs, playable) == s


def test_parse_board_semantics():
    pegs, playable = parse_board("10" + "2" * 23)
    assert pegs == 0b01 and playable == 0b11


def test_parse_board_bad_length():
    with pytest.raises(ValueError):
        parse_board("111")


def test_pretty_board_reference_orientation():
    # Reference Print is column-major: output row r lists cells (i, j=r)
    # for i = 0..4 (game.cc:108-118). Cell 5 is (i=1, j=0) -> row 0 col 1.
    pegs, playable = parse_board("0" * 5 + "1" + "0" * 19)
    lines = pretty_board(pegs, playable).splitlines()
    assert lines[0] == "*X***"
    assert all(ln == "*****" for ln in lines[1:])


# ---------------------------------------------------------------------------
# Game rules

def test_apply_move_jump():
    # Pegs at (0,1) and (0,2); jump (0,2) over (0,1) into hole (0,0):
    # move = cell 0, dir 2 (mid (0,1), far (0,2)).
    pegs, playable = parse_board("0110" + "0" * 21)
    m = 0 * 4 + 2
    assert replay_moves(pegs, playable, [m])[-1] == 0b1
    assert apply_move(pegs, m) == 0b1


def test_replay_rejects_illegal_move():
    pegs, playable = parse_board("0110" + "0" * 21)
    with pytest.raises(ValueError):
        replay_moves(pegs, playable, [0 * 4 + 0])


def test_single_peg_is_immediate_win():
    b = BoardBatch.from_strings(["1" + "0" * 24])
    solved, n_moves, moves, steps, status = solve_batch(b.pegs, b.playable)
    assert bool(solved[0]) and int(n_moves[0]) == 0
    assert int(status[0]) == SOLVED


def test_empty_and_full_boards_unsolvable():
    # No pegs: not a win (win == exactly one peg). All pegs: no hole to
    # jump into, >1 peg -> exhausted immediately.
    b = BoardBatch.from_strings(["0" * 25, "1" * 25])
    solved, _, _, steps, status = solve_batch(b.pegs, b.playable)
    assert not solved.any()
    assert list(np.asarray(status)) == [EXHAUSTED, EXHAUSTED]


def test_three_in_a_row_unsolvable():
    # Classic: 3 pegs in a line can never reduce to 1.
    b = BoardBatch.from_strings(["111" + "0" * 22])
    solved, *_ = solve_batch(b.pegs, b.playable)
    assert not bool(solved[0])


def test_domino_solvable_in_one_move():
    # Pegs at (0,0), (0,1): peg (0,0) jumps over (0,1) into hole (0,2).
    board = "110" + "0" * 22
    pegs, playable = parse_board(board)
    ok, moves, _ = solve_one_py(pegs, playable)
    assert ok and len(moves) == 1
    assert moves == [2 * 4 + 3]  # dest cell (0,2), dir 3 (mid/far leftward)
    assert bin(replay_moves(pegs, playable, moves)[-1]).count("1") == 1


def test_square_solvable_in_three_moves():
    # 2x2 peg square at the corner reduces to one peg in 3 jumps.
    board = "11000" + "11000" + "0" * 15
    pegs, playable = parse_board(board)
    ok, moves, _ = solve_one_py(pegs, playable)
    assert ok and len(moves) == 3
    assert bin(replay_moves(pegs, playable, moves)[-1]).count("1") == 1


def test_na_cells_block_jumps():
    # The domino's only escape hole (0,2) marked NA makes it unsolvable,
    # for both oracle and kernel (NA cells are never valid destinations,
    # game.cc:78-81: destination must be HOLE).
    blocked = "112" + "0" * 22
    ok_blocked, _, _ = solve_one_py(*parse_board(blocked))
    assert not ok_blocked
    b = BoardBatch.from_strings([blocked])
    solved, *_ = solve_batch(b.pegs, b.playable)
    assert not bool(solved[0])


# ---------------------------------------------------------------------------
# JAX solver vs Python oracle (the core parity property)

@pytest.mark.parametrize("grade", ["easy", "medium"])
def test_solver_matches_oracle(grade):
    ds = generate_dataset(48, grade, seed=7)
    solved, n_moves, moves, steps, status = (
        np.asarray(x) for x in solve_batch(ds.pegs, ds.playable))
    for i in range(len(ds)):
        ok, ms, nodes = solve_one_py(int(ds.pegs[i]), int(ds.playable[i]))
        assert ok == bool(solved[i]), f"board {i}: solved mismatch"
        assert nodes == int(steps[i]), f"board {i}: node-count mismatch"
        if ok:
            got = list(moves[i][:n_moves[i]])
            assert got == ms, f"board {i}: move-sequence mismatch"
            final = replay_moves(int(ds.pegs[i]), int(ds.playable[i]), got)[-1]
            assert bin(final).count("1") == 1


def test_solver_first_solution_is_lexicographic_dfs():
    # Move order is (i, j, dir) lexicographic as in validMoveList
    # (game.cc:99-107); the solver must return the FIRST solution in
    # that order, not just any solution.
    ds = generate_dataset(16, "easy", seed=3)
    _, n_moves, moves, _, _ = (
        np.asarray(x) for x in solve_batch(ds.pegs, ds.playable))
    for i in range(len(ds)):
        ok, ms, _ = solve_one_py(int(ds.pegs[i]), int(ds.playable[i]))
        if ok:
            assert list(moves[i][:n_moves[i]]) == ms


def test_step_limit_status():
    ds = generate_dataset(8, "medium", seed=11, solvable_fraction=0.0)
    solved, _, _, steps, status = (
        np.asarray(x) for x in solve_batch(ds.pegs, ds.playable, max_steps=3))
    assert (steps <= 3).all()
    assert (status[~solved] == STEP_LIMIT).any() or solved.all()


def test_solvable_generator_always_solvable():
    ds = generate_dataset(32, "easy", seed=5, solvable_fraction=1.0)
    solved, *_ = solve_batch(ds.pegs, ds.playable)
    assert np.asarray(solved).all()


# ---------------------------------------------------------------------------
# Golden solution counts (deterministic datasets -> fixed counts)

GOLDEN = {("easy", 0, 128): None}  # filled by the oracle below, once


def test_golden_count_stable_across_schedulers(tmp_path):
    ds = generate_dataset(128, "easy", seed=0)
    oracle = sum(
        solve_one_py(int(ds.pegs[i]), int(ds.playable[i]))[0]
        for i in range(len(ds)))
    static = solve_static(ds)
    dynamic = solve_dynamic(ds, chunk_size=8)
    assert static.n_solutions == oracle
    assert dynamic.n_solutions == oracle
    assert (static.solved == dynamic.solved).all()
    assert (static.steps == dynamic.steps).all()


# ---------------------------------------------------------------------------
# Dataset I/O

def test_dataset_roundtrip(tmp_path):
    ds = generate_dataset(20, "easy", seed=2)
    path = tmp_path / "games.dat"
    save_dataset(path, ds)
    back = load_dataset(path)
    assert (back.pegs == ds.pegs).all()
    assert (back.playable == ds.playable).all()
    first = open(path).readline().strip()
    assert first == "20"  # reference header: count line (main.cc:52)


def test_dataset_gzip_roundtrip(tmp_path):
    ds = generate_dataset(10, "medium", seed=4)
    path = str(tmp_path / "games.dat.gz")
    save_dataset(path, ds)
    back = load_dataset(path)
    assert (back.pegs == ds.pegs).all()


def test_dataset_bad_header(tmp_path):
    p = tmp_path / "bad.dat"
    p.write_text("5\n" + "1" * 25 + "\n")
    with pytest.raises(ValueError):
        load_dataset(p)


def test_reference_format_compatibility():
    # A row from the reference's easy_sample.dat parses cleanly
    # (SURVEY.md C28 format: '0'/'1'/'2' chars).
    row = "2111210112221122212222222"
    pegs, playable = parse_board(row)
    assert bin(pegs).count("1") == row.count("1")
    assert bin(playable).count("1") == row.count("1") + row.count("0")


# ---------------------------------------------------------------------------
# Schedulers

def test_static_uses_multiple_devices():
    ds = generate_dataset(64, "easy", seed=9)
    rep = solve_static(ds)
    p = min(len(jax.devices()), 64)
    assert len(rep.per_worker_games) == p
    assert sum(rep.per_worker_games) == 64
    assert rep.imbalance >= 1.0


def test_dynamic_chunk_accounting():
    ds = generate_dataset(50, "easy", seed=10)  # 50 = 6x8 + 2: ragged tail
    rep = solve_dynamic(ds, chunk_size=8)
    assert sum(rep.per_worker_games) == 50
    assert rep.n_solutions == solve_static(ds).n_solutions


def test_dynamic_empty_batch():
    ds = generate_dataset(0, "easy", seed=0)
    rep = solve_dynamic(ds)
    assert rep.n_solutions == 0 and len(rep.solved) == 0


def test_padding_boards_never_count_as_solutions():
    # 9 games with chunk 8 -> 7 empty padding boards in chunk 2; empty
    # boards must not inflate the count (win requires exactly one peg).
    ds = generate_dataset(9, "easy", seed=12, solvable_fraction=1.0)
    rep = solve_dynamic(ds, chunk_size=8)
    assert rep.n_solutions == 9


def test_write_solutions_renders_replayable(tmp_path):
    ds = generate_dataset(12, "easy", seed=13, solvable_fraction=1.0)
    rep = solve_static(ds)
    out = tmp_path / "solutions.txt"
    n = write_solutions(out, ds, rep)
    assert n == 12
    text = out.read_text()
    assert "-->" in text
    # Every rendered state line uses the reference Print alphabet.
    for line in text.splitlines():
        assert set(line) <= set("X* -\n>")


def test_render_solution_shape():
    board = "11000" + "11000" + "0" * 15
    pegs, playable = parse_board(board)
    ok, moves, _ = solve_one_py(pegs, playable)
    assert ok
    text = render_solution(board, moves)
    # len(moves) transitions -> len(moves)+1 board renderings.
    assert text.count("-->") == len(moves)


# ---------------------------------------------------------------------------
# Checkpoint / resume (SURVEY.md §5.4 upgrade)


def test_dynamic_checkpoint_resume(tmp_path):
    """A restarted dynamic run must load finished chunks from the
    checkpoint instead of recomputing them."""
    from icikit.models.solitaire.scheduler import (
        ChunkCheckpoint,
        checkpoint_fingerprint,
    )

    ds = generate_dataset(32, "easy", seed=21)
    ck = tmp_path / "run.ckpt"

    full = solve_dynamic(ds, chunk_size=8, checkpoint_path=str(ck))
    assert ck.exists()

    # Forge a checkpoint holding only chunk 0, with a marker steps value
    # no real solve would produce — a resumed run must carry it through
    # verbatim, proving chunk 0 was loaded, not re-solved.
    fp = checkpoint_fingerprint(ds, 8, 2_000_000_000)
    ck2 = tmp_path / "partial.ckpt"
    store = ChunkCheckpoint(str(ck2), fp)
    marker = tuple(np.asarray(a) for a in (
        full.solved[:8], full.n_moves[:8], full.moves[:8],
        np.full(8, 999_999, np.int32), full.status[:8]))
    store.add(0, marker)

    resumed = solve_dynamic(ds, chunk_size=8, checkpoint_path=str(ck2))
    assert (resumed.steps[:8] == 999_999).all()          # loaded chunk
    np.testing.assert_array_equal(resumed.solved, full.solved)
    np.testing.assert_array_equal(resumed.steps[8:], full.steps[8:])


def test_checkpoint_refuses_wrong_dataset(tmp_path):
    from icikit.models.solitaire.scheduler import (
        ChunkCheckpoint,
        checkpoint_fingerprint,
    )
    ds_a = generate_dataset(16, "easy", seed=1)
    ds_b = generate_dataset(16, "easy", seed=2)
    ck = tmp_path / "a.ckpt"
    solve_dynamic(ds_a, chunk_size=8, checkpoint_path=str(ck))
    with pytest.raises(ValueError, match="different dataset"):
        solve_dynamic(ds_b, chunk_size=8, checkpoint_path=str(ck))
    # same dataset but different chunking is also a different run shape
    with pytest.raises(ValueError, match="different dataset"):
        solve_dynamic(ds_a, chunk_size=4, checkpoint_path=str(ck))


def test_checkpoint_survives_torn_tail(tmp_path):
    """A crash mid-append leaves a torn last line; resume must ignore it
    and re-solve that chunk."""
    ds = generate_dataset(16, "easy", seed=9)
    ck = tmp_path / "torn.ckpt"
    full = solve_dynamic(ds, chunk_size=8, checkpoint_path=str(ck))
    with open(ck, "a") as f:
        f.write('{"chunk": 1, "solved": [tru')  # torn write
    resumed = solve_dynamic(ds, chunk_size=8, checkpoint_path=str(ck))
    np.testing.assert_array_equal(resumed.solved, full.solved)


@pytest.mark.slow
def test_host_pool_reproduces_modeled_schedule_ranking():
    """VERDICT r4 #8: the DLB schedule-quality claim, executable on the
    live pool. simulate_schedule's virtual-clock replay says dynamic
    chunking beats a static contiguous split on the skewed set; the
    native thread pool (with r5's board->worker telemetry) must
    reproduce that ranking, and the per-worker load splits must agree
    with the virtual-clock model up to queue racing (the pool is a
    pull queue even at one-chunk-per-worker sizing: a fast-starting
    thread can take two chunks, so groupings — not totals — race;
    measured 2026-07-31: static imbalance 4.363 live vs 4.358
    modeled, dynamic 1.760 vs 1.773)."""
    from icikit import native
    from icikit.models.solitaire.dataset import generate_skewed_dataset
    from icikit.models.solitaire.scheduler import (
        simulate_schedule, solve_host)

    if not native.available():
        pytest.skip(native.build_error() or "no native runtime")

    n_workers, chunk, max_steps = 8, 4, 500_000
    skewed = generate_skewed_dataset(256, seed=3, hard_fraction=0.25)
    host_static = solve_host(skewed, n_threads=n_workers,
                             chunk_size=-(-len(skewed) // n_workers),
                             max_steps=max_steps)
    host_dynamic = solve_host(skewed, n_threads=n_workers,
                              chunk_size=chunk, max_steps=max_steps)
    assert host_static.n_solutions == host_dynamic.n_solutions

    # the model replays the MEASURED per-board costs (identical for
    # both runs: DFS node counts are deterministic)
    np.testing.assert_array_equal(host_static.steps, host_dynamic.steps)
    sim_st = simulate_schedule(host_static.steps, n_workers, "static")
    sim_dy = simulate_schedule(host_static.steps, n_workers, "dynamic",
                               chunk_size=chunk)

    def imb(per):
        per = np.asarray(per, np.float64)
        return per.max() / per.mean()

    # 1. the modeled ranking (the claim NORTHSTAR narrates)
    assert imb(sim_dy) < imb(sim_st)

    # 2. the live imbalances agree with the model: static's max is
    #    pinned by the dominant indivisible hard chunk — redistributing
    #    every easy chunk moves max/mean by < 1%, and the only way to
    #    blow the bound is one worker taking BOTH hard chunks, which
    #    requires it to finish a seconds-long DFS before any of 7 peers
    #    performs a microsecond queue pull. 10% margin covers the
    #    easy-chunk shuffle with room. Dynamic races on a timeshared
    #    host (loose margin, still far from static's 4x+ skew).
    assert abs(imb(host_static.per_worker_steps)
               - imb(sim_st)) < 0.10 * imb(sim_st)
    assert abs(imb(host_dynamic.per_worker_steps)
               - imb(sim_dy)) < 0.25 * imb(sim_dy)

    # 3. the live pool reproduces the ranking — dynamic spreads the
    #    hard tail static concentrates — and the dynamic per-worker
    #    load ORDERING tracks the model worker-for-worker (sorted)
    assert imb(host_dynamic.per_worker_steps) < imb(
        host_static.per_worker_steps)
    np.testing.assert_allclose(
        np.sort(np.asarray(host_dynamic.per_worker_steps, np.float64)),
        np.sort(np.asarray(sim_dy, np.float64)), rtol=0.25)

    # 4. chunk conservation: every dynamic chunk went to exactly one
    #    worker (the queue hands out whole chunks)
    _, _, _, _, workers = native.solve_batch(
        skewed.pegs, skewed.playable, max_steps=max_steps,
        n_threads=n_workers, chunk_size=chunk, return_workers=True)
    for c0 in range(0, len(skewed), chunk):
        assert len(set(workers[c0:c0 + chunk])) == 1


# ---------------------------------------------------------------------------
# Self-healing dynamic schedule: lease queue + hardened checkpoint
# (fast, queue-level drills; full-pipeline chaos soaks live in
# tests/test_chaos_soak.py)


def _queue(chunks, lease_s=60.0, workers=2):
    from icikit.models.solitaire.scheduler import _LeaseQueue
    return _LeaseQueue(list(range(chunks)), lease_s, workers)


def test_lease_queue_death_reissues_inflight_chunks():
    q = _queue(4, workers=2)
    mine = q.claim(0, p=2, max_pull=2)
    assert mine  # leased to worker 0
    q.mark_dead(0, RuntimeError("boom"))
    assert q.reissues == len(mine)
    # the survivor drains everything, including the reissued chunks
    seen = []
    while True:
        got = q.claim(1, p=2, max_pull=4)
        if not got:
            break
        for c in got:
            assert q.commit(1, c, games=1, steps=1)
        seen += got
    assert sorted(seen) == [0, 1, 2, 3]
    assert q.deaths.keys() == {0}


def test_lease_queue_expired_lease_reissues_and_late_commit_is_noop():
    q = _queue(2, lease_s=0.0, workers=2)  # leases expire immediately
    hung = q.claim(0, p=2, max_pull=1)
    assert hung == [0]
    # worker 1 pulls: the expired lease is reaped and chunk 0 reissued
    got = []
    while len(got) < 2:
        pulled = q.claim(1, p=2, max_pull=1)
        assert pulled
        got += pulled
        assert q.commit(1, pulled[0], games=1, steps=1)
    assert sorted(got) == [0, 1]
    assert q.reissues >= 1
    # the hung worker finally finishes: duplicate commit changes nothing
    assert q.commit(0, 0, games=1, steps=1) is False
    assert q.per_games[0] == 0  # first commit won the telemetry
    assert q.claim(0, p=2, max_pull=1) == []  # drained


def test_lease_queue_no_survivors_raises_promptly():
    import time as _time

    from icikit.models.solitaire.scheduler import NoSurvivorsError
    q = _queue(4, workers=2)
    q.claim(0, p=2, max_pull=1)
    t0 = _time.monotonic()
    q.mark_dead(0, RuntimeError("first"))
    q.mark_dead(1, ValueError("second"))
    with pytest.raises(NoSurvivorsError) as ei:
        q.wait_drained()
    # prompt: no join over threads that will never return
    assert _time.monotonic() - t0 < 5.0
    assert ei.value.deaths.keys() == {0, 1}
    assert "worker 0" in str(ei.value) and "worker 1" in str(ei.value)
    assert "2 workers died" in str(ei.value)


def test_solve_dynamic_all_workers_dead_error_telemetry():
    """End-to-end: every worker dies -> NoSurvivorsError with per-worker
    telemetry, raised without waiting on wedged joins."""
    from icikit import chaos
    from icikit.models.solitaire.scheduler import NoSurvivorsError

    ds = generate_dataset(16, "easy", seed=3)
    p = min(2, jax.device_count())
    plan = chaos.FaultPlan(schedule={
        f"die:solitaire.worker.{w}": (0,) for w in range(p)})
    with chaos.inject(plan):
        with pytest.raises(NoSurvivorsError) as ei:
            solve_dynamic(ds, devices=jax.devices()[:p], chunk_size=4)
    assert sorted(ei.value.deaths) == list(range(p))
    assert all(isinstance(e, chaos.InjectedDeath)
               for e in ei.value.deaths.values())


def test_chunk_checkpoint_skips_corrupt_but_parseable_records(tmp_path):
    """A bit-flipped-on-disk record that still parses as JSON (wrong
    lengths, wrong chunk index, wrong types) must be skipped like a
    torn tail — never crash the post-join concatenate."""
    import json as _json

    from icikit.models.solitaire.scheduler import ChunkCheckpoint

    ds = generate_dataset(16, "easy", seed=9)
    ck = tmp_path / "c.ckpt"
    full = solve_dynamic(ds, chunk_size=8, checkpoint_path=str(ck))

    good = _json.loads(open(ck).readlines()[1])
    bad = [
        dict(good, solved=good["solved"][:-1]),        # short array
        dict(good, chunk="one"),                       # bogus index
        dict(good, chunk=-2),
        dict(good, n_moves="abc"),                     # wrong type
        dict(good, moves=[[0] * 3] * 8),               # wrong width
        dict(good, steps=None),
    ]
    with open(ck, "a") as f:
        for rec in bad:
            f.write(_json.dumps(rec) + "\n")

    from icikit.models.solitaire.scheduler import checkpoint_fingerprint
    fp = checkpoint_fingerprint(ds, 8, 2_000_000_000)
    store = ChunkCheckpoint(str(ck), fp, chunk_size=8)
    assert store.n_skipped == len(bad)

    resumed = solve_dynamic(ds, chunk_size=8, checkpoint_path=str(ck))
    np.testing.assert_array_equal(resumed.solved, full.solved)
    np.testing.assert_array_equal(resumed.steps, full.steps)


def test_chunk_checkpoint_duplicates_are_last_writer_wins(tmp_path):
    """Reissue writes can record one chunk twice; load must keep the
    LAST record (both are correct in production — the solver is
    deterministic — but the contract must be pinned)."""
    from icikit.models.solitaire.game import MAX_DEPTH
    from icikit.models.solitaire.scheduler import ChunkCheckpoint

    ck = tmp_path / "dup.ckpt"
    store = ChunkCheckpoint(str(ck), "fp", chunk_size=4)

    def rec(tag):
        return (np.zeros(4, bool), np.zeros(4, np.int32),
                np.full((4, MAX_DEPTH), -1, np.int32),
                np.full(4, tag, np.int32), np.zeros(4, np.int32))

    store.add(0, rec(111))
    store.add(0, rec(222))  # the reissue's duplicate
    again = ChunkCheckpoint(str(ck), "fp", chunk_size=4)
    assert list(again.loaded) == [0]
    assert (again.loaded[0][3] == 222).all()


def test_chunk_checkpoint_sealed_after_close_drops_late_adds(tmp_path):
    """A hung worker abandoned by solve_dynamic's bounded join may wake
    after the run returned and the caller reused the path for other
    work — its late add() on the sealed store must be dropped, not
    appended past the new run's fingerprint guard."""
    from icikit.models.solitaire.game import MAX_DEPTH
    from icikit.models.solitaire.scheduler import ChunkCheckpoint

    ck = tmp_path / "sealed.ckpt"
    store = ChunkCheckpoint(str(ck), "fp", chunk_size=2)
    arrays = (np.zeros(2, bool), np.zeros(2, np.int32),
              np.full((2, MAX_DEPTH), -1, np.int32),
              np.zeros(2, np.int32), np.zeros(2, np.int32))
    store.add(0, arrays)
    store.close()
    store.add(1, arrays)  # the straggler's stale write
    assert list(ChunkCheckpoint(str(ck), "fp", chunk_size=2).loaded) \
        == [0]


def test_chunk_checkpoint_add_retries_transient_io_failures(tmp_path):
    """One flaky write must not kill a worker: add() retries with
    bounded backoff (first two attempts fail here, third lands)."""
    from icikit import chaos
    from icikit.models.solitaire.game import MAX_DEPTH
    from icikit.models.solitaire.scheduler import ChunkCheckpoint

    ck = tmp_path / "flaky.ckpt"
    store = ChunkCheckpoint(str(ck), "fp", chunk_size=2)
    arrays = (np.zeros(2, bool), np.zeros(2, np.int32),
              np.full((2, MAX_DEPTH), -1, np.int32),
              np.zeros(2, np.int32), np.zeros(2, np.int32))
    plan = chaos.FaultPlan(
        schedule={"io:solitaire.ckpt.write": (0, 1, 3)})
    with chaos.inject(plan):
        store.add(0, arrays)                    # retried internally
        with pytest.raises(OSError):
            store.add(1, arrays, retries=0)     # retries exhausted
    assert plan.fired("io") == 3
    assert list(ChunkCheckpoint(str(ck), "fp", chunk_size=2).loaded) \
        == [0]


def test_lease_queue_late_commit_cancels_pending_reissue():
    """A straggler whose lease was reaped may still finish first: its
    commit must retire the chunk AND pull it back out of the queue so
    no survivor re-solves finished work."""
    q = _queue(1, lease_s=0.0, workers=1)
    assert q.claim(0, p=1, max_pull=1) == [0]
    with q._cv:                     # reap without a competing claim
        q._reap_expired()
    assert list(q._todo) == [0] and q.reissues == 1
    assert q.commit(0, 0, games=1, steps=1) is True
    assert not q._todo              # the pending reissue was cancelled
    assert q.claim(0, p=1, max_pull=1) == []  # drained


def test_solve_dynamic_partial_death_warns_and_reports_errors():
    """A healed run must not hide the error that killed a worker: it
    lands in SolveReport.death_errors and a RuntimeWarning."""
    from icikit import chaos

    ds = generate_dataset(16, "easy", seed=5)
    p = min(2, jax.device_count())
    plan = chaos.FaultPlan(schedule={"die:solitaire.worker.1": (0,)})
    with chaos.inject(plan):
        with pytest.warns(RuntimeWarning, match="worker 1"):
            rep = solve_dynamic(ds, devices=jax.devices()[:p],
                                chunk_size=4)
    assert rep.n_deaths == 1 and rep.worker_deaths == [1]
    assert len(rep.death_errors) == 1
    assert "InjectedDeath" in rep.death_errors[0]
