"""Fault tolerance end-to-end: SIGKILL a training run mid-flight, then
restart and finish from the last committed checkpoint.

The reference's whole failure story is fail-fast (signal traps +
watchdog -> MPI_Abort, SURVEY.md §5.3) — partial DLB results surviving
a crash was an accident of output streaming. Here recovery is
deliberate: Orbax commits checkpoints atomically, so an abrupt kill
(not even SIGTERM) leaves a consistent latest step for auto-resume."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

ARGS = ["--steps", "40", "--batch", "4", "--vocab", "32",
        "--d-model", "32", "--n-heads", "2", "--d-head", "8",
        "--d-ff", "64", "--n-layers", "1", "--seq", "16",
        "--compute-dtype", "float32", "--log-every", "5",
        "--ckpt-every", "2", "--sample-tokens", "0"]


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    return env


def _committed_steps(ckpt_dir):
    try:
        return [d for d in os.listdir(ckpt_dir) if d.isdigit()]
    except FileNotFoundError:
        return []


@pytest.mark.slow
def test_sigkill_mid_run_then_resume(tmp_path):
    ckpt = str(tmp_path / "run")
    cmd = [sys.executable, "-m", "icikit.models.transformer.train",
           "--ckpt-dir", ckpt, *ARGS]
    proc = subprocess.Popen(cmd, env=_env(), stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, text=True)
    try:
        deadline = time.time() + 240
        while time.time() < deadline:
            if _committed_steps(ckpt):
                break  # kill at the FIRST committed checkpoint
            if proc.poll() is not None:
                pytest.fail("training exited before any checkpoint "
                            f"(rc={proc.returncode})")
            time.sleep(0.05)
        else:
            pytest.fail("no checkpoint appeared within the deadline")
        if proc.poll() is not None:
            # whole tiny run outran the poll: crash semantics untestable
            pytest.skip("run finished before it could be killed")
        proc.send_signal(signal.SIGKILL)  # abrupt: no cleanup at all
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()

    out = subprocess.run(cmd, env=_env(), capture_output=True,
                         text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    recs = [json.loads(line) for line in out.stdout.splitlines()]
    resumed = [r for r in recs if r.get("event") == "resumed"]
    assert resumed, "second run did not resume from the kill survivor"
    assert resumed[0]["step"] >= 2
    steps = [r["step"] for r in recs if "step" in r and "loss" in r]
    assert steps and steps[-1] == 40  # ran to completion
