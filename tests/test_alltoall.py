"""Pattern-oracle tests for the all-to-all (personalized) family.

Ports the reference's verification (``Communication/src/main.cc:465-486``):
send buffers hold a (src, dst, element)-derived pattern; after the
collective, device d must hold block ``x[s, d]`` in slot s for all s —
i.e. the result equals the global transpose ``swapaxes(x, 0, 1)``.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from icikit.parallel import ALLTOALL_ALGORITHMS, all_to_all_blocks
from icikit.utils.mesh import make_mesh, shard_along


def _pattern(p, m, it=0):
    src = np.arange(p)[:, None, None]
    dst = np.arange(p)[None, :, None]
    k = np.arange(m)[None, None, :]
    return (src * 10000 + dst * 100 + k + it).astype(np.int32)


@pytest.mark.parametrize("algorithm", ALLTOALL_ALGORITHMS)
@pytest.mark.parametrize("m", [1, 16, 128])
def test_alltoall_transpose_oracle(mesh8, algorithm, m):
    p = 8
    data = _pattern(p, m)
    x = shard_along(jnp.asarray(data), mesh8)
    out = np.asarray(all_to_all_blocks(x, mesh8, algorithm=algorithm))
    np.testing.assert_array_equal(out, data.swapaxes(0, 1))


@pytest.mark.parametrize("algorithm", ALLTOALL_ALGORITHMS)
def test_alltoall_repeated_runs_stable(mesh8, algorithm):
    p, m = 8, 16
    for it in range(5):
        data = _pattern(p, m, it)
        x = shard_along(jnp.asarray(data), mesh8)
        out = np.asarray(all_to_all_blocks(x, mesh8, algorithm=algorithm))
        np.testing.assert_array_equal(out, data.swapaxes(0, 1))


@pytest.mark.parametrize("algorithm", ["wraparound", "naive", "xla"])
def test_alltoall_non_power_of_two(algorithm):
    p, m = 6, 4
    mesh = make_mesh(p)
    data = _pattern(p, m)
    x = shard_along(jnp.asarray(data), mesh)
    out = np.asarray(all_to_all_blocks(x, mesh, algorithm=algorithm))
    np.testing.assert_array_equal(out, data.swapaxes(0, 1))


@pytest.mark.parametrize("algorithm", ["ecube", "hypercube"])
def test_hypercube_family_rejects_non_pow2(algorithm):
    mesh = make_mesh(6)
    x = shard_along(jnp.zeros((6, 6, 2), jnp.int32), mesh)
    with pytest.raises(ValueError, match="power-of-2"):
        all_to_all_blocks(x, mesh, algorithm=algorithm)


@pytest.mark.parametrize("algorithm", ALLTOALL_ALGORITHMS)
def test_alltoall_p4_double(mesh4, algorithm):
    p, m = 4, 8
    rng = np.random.default_rng(1)
    data = rng.standard_normal((p, p, m)).astype(np.float32)
    x = shard_along(jnp.asarray(data), mesh4)
    out = np.asarray(all_to_all_blocks(x, mesh4, algorithm=algorithm))
    np.testing.assert_array_equal(out, data.swapaxes(0, 1))
