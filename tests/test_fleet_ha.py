"""Fleet HA (r18): journal replay, leader lease, failover, roster.

The process-local half of the kill-the-leader story (the subprocess
soak lives in tests/test_fleet_ha_soak.py):

- journal framing survives a round trip; a torn tail (writer died
  mid-record) is detected and replay stops at the last good record;
- **replay determinism** (the property test): a live RequestQueue
  driven through a seeded random verb storm journals records whose
  EVERY prefix replays to the live queue's digest at that point —
  effects-based records re-apply decisions, they never re-make them;
- snapshots compact: after ``checkpoint`` the journal is one segment
  whose first record rebuilds the whole queue, and a tailing standby
  rides the compaction without losing state;
- the leader lease: acquire/renew/depose ordering, the corrupt-file
  drill (one rotten read is UNKNOWN, two promote over the journal's
  epoch floor), and the double-leader epoch-collision drill recovered
  through the journal's O_EXCL backstop;
- in-process failover: a coordinator that stops renewing is replaced
  by a standby whose replayed queue still holds the in-flight
  request; the deposed leader answers every mutation with
  ``DeposedError`` and a ``LeaderClient`` retargets through the
  lease file;
- elastic roster: token-authenticated join (bad token → denied +
  counted) and graceful retire (no further claims; ``drained``
  answers per-engine once its plate is clean).

No jax: everything here is control plane.
"""

import threading
import time

import numpy as np
import pytest

from icikit import chaos, obs
from icikit.fleet import journal as jlog
from icikit.fleet.coordinator import Coordinator
from icikit.fleet.ha import (
    LeaderClient,
    LeaderLease,
    Standby,
    become_leader,
)
from icikit.fleet.transport import RpcClient, RpcError
from icikit.serve.scheduler import RequestQueue


def _mkq(journal=None, lease_s=30.0):
    q = RequestQueue(lease_s=lease_s)
    if journal is not None:
        q.journal = journal
    return q


def _submit(q, rng, n_new=None):
    return q.submit(
        rng.integers(0, 64, (int(rng.integers(2, 8)),))
        .astype(np.int32),
        int(n_new if n_new is not None else rng.integers(1, 6)),
        max_retries=3, seed=int(rng.integers(0, 100)),
        temperature=float(rng.choice([0.0, 0.7])))


# -- journal file format ---------------------------------------------


def test_journal_roundtrip_and_torn_tail(tmp_path):
    d = str(tmp_path)
    j = jlog.Journal(d)
    j.start(1)
    recs = [("submit", {"rid": f"r{i}", "seq": i, "prompt": [i],
                        "n_new": 2, "eos_id": None, "vis": 0.0,
                        "max_retries": 2, "quant": False, "seed": 0,
                        "temperature": 0.0, "top_k": 0, "top_p": 1.0,
                        "trace_id": f"t{i}"}) for i in range(4)]
    for v, r in recs:
        j.append(v, r)
    j.close()
    seg = jlog.segments(d)
    assert seg == ["seg-00000001-00000000.log"]
    path = tmp_path / "journal" / seg[0]
    got, end, status = jlog.read_records(str(path))
    assert status == "ok" and got == recs
    assert end == path.stat().st_size
    # tear the tail: drop 5 bytes off the last record — the reader
    # must surface every record before it and flag the damage
    raw = path.read_bytes()
    path.write_bytes(raw[:-5])
    got, _, status = jlog.read_records(str(path))
    assert status == "partial" and got == recs[:-1]
    # corrupt (not truncate) the tail: checksum catches it as torn
    bad = bytearray(raw)
    bad[-3] ^= 0xFF
    path.write_bytes(bytes(bad))
    got, _, status = jlog.read_records(str(path))
    assert status == "torn" and got == recs[:-1]


def test_journal_epoch_collision_is_excl(tmp_path):
    d = str(tmp_path)
    a = jlog.Journal(d)
    a.start(3)
    b = jlog.Journal(d)
    with pytest.raises(jlog.EpochCollision):
        b.start(3)
    a.close()
    assert jlog.epoch_floor(d) == 3


# -- replay determinism (the property test) --------------------------


def _drive(q, rng, n_ops, eos_every=0):
    """One seeded storm of live verbs against ``q``; returns the rids
    it touched. Covers every journaled verb: submit, claim (incl.
    drops via max_retries exhaustion), complete (incl. duplicate),
    handoff (partial stream → requeued), fail (retry and terminal),
    release, expire→reap, stamp_marks."""
    claimed = []
    for _ in range(n_ops):
        op = rng.integers(0, 10)
        if op <= 2 or not claimed:
            _submit(q, rng)
            r = q.claim()
            if r is not None:
                claimed.append(r)
        elif op == 3:
            r = q.claim()
            if r is not None:
                claimed.append(r)
        elif op == 4:
            r = claimed.pop(rng.integers(0, len(claimed)))
            q.complete(r.rid, [1, 2, 3][:max(1, r.n_new)],
                       seq=r.claim_seq)
            if rng.integers(0, 2):     # duplicate commit path
                q.complete(r.rid, [9], seq=r.claim_seq)
        elif op == 5:
            r = claimed.pop(rng.integers(0, len(claimed)))
            q.handoff(r.rid, [7], seq=r.claim_seq)
        elif op == 6:
            r = claimed.pop(rng.integers(0, len(claimed)))
            q.fail(r.rid, RuntimeError("boom"),
                   retry=bool(rng.integers(0, 2)), seq=r.claim_seq)
        elif op == 7:
            r = claimed.pop(rng.integers(0, len(claimed)))
            q.release(r.rid, delay=0.0, seq=r.claim_seq)
        elif op == 8:
            r = claimed.pop(rng.integers(0, len(claimed)))
            q.expire([r.rid])
            q.reap_expired()
        else:
            r = claimed[rng.integers(0, len(claimed))]
            q.stamp_marks(r.rid, {
                "admit_t": 1.0, "first_token_t": 2.0,
                "max_gap_ms": float(rng.integers(1, 50)),
                "prefix_hit_tokens": int(rng.integers(0, 4))})


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_replay_every_prefix_is_bitwise(seed):
    """Any prefix of the verb log replays to the live queue's exact
    digest at that point — the journal's core contract."""
    records, digests = [], []

    def tap(verb, rec):
        records.append((verb, rec))

    q = _mkq(journal=tap)
    rng = np.random.default_rng(seed)
    n_before = 0
    for _ in range(40):
        _drive(q, rng, 1)
        if len(records) != n_before:
            # digest after each batch of appends: live state at every
            # record boundary the storm produced
            digests.append((len(records), q.state_digest()))
            n_before = len(records)
    assert records, "storm journaled nothing"
    for upto, want in digests:
        rq, _meta = jlog.replay_records(records[:upto])
        assert rq.state_digest() == want, \
            f"seed {seed}: prefix {upto}/{len(records)} diverged"


def test_replay_through_snapshot_is_bitwise(tmp_path):
    """A snapshot mid-stream supersedes the records before it: replay
    of snap+tail equals live, and the compacted on-disk journal
    rebuilds the same digest through the file path too."""
    d = str(tmp_path)
    j = jlog.Journal(d)
    j.start(1)
    q = _mkq(journal=j.append)
    rng = np.random.default_rng(7)
    _drive(q, rng, 12)
    assert q.checkpoint(meta={"phases": {}, "owners": {},
                             "n_handoffs": 0}) is not None
    _drive(q, rng, 12)
    live = q.state_digest()
    j.close()
    assert len(jlog.segments(d)) == 1      # compaction ran
    rq, _meta, info = jlog.replay(d)
    assert rq.state_digest() == live
    assert info["torn"] == 0
    # the replayed queue mints FRESH rids above the journaled range —
    # no collision with anything the previous life handed out
    new_rid = _submit(rq, rng)
    assert new_rid not in {r for r in q._requests}


def test_replayed_leader_continues_bitwise():
    """A successor restored from the journal keeps tracking the live
    queue verb-for-verb: after replay, every further journaled verb
    replays onto the replica to the exact live digest.  (Parallel
    live driving can NOT be the bar — verbs stamp wall-clock instants
    like ``visible_after`` at append time, so two live queues diverge
    by nanoseconds; the journal records those instants, which is
    precisely why replay is exact.)"""
    records = []
    q = _mkq(journal=lambda v, r: records.append((v, r)))
    rng = np.random.default_rng(11)
    _drive(q, rng, 25)
    rq, _ = jlog.replay_records(records)
    assert rq.state_digest() == q.state_digest()
    # continuation: keep journaling the live queue and check the
    # replica stays digest-locked at every step of the tail
    for _ in range(15):
        _drive(q, rng, 1)
        rq, _ = jlog.replay_records(records)
        assert rq.state_digest() == q.state_digest()


def test_journal_tail_rides_compaction(tmp_path):
    d = str(tmp_path)
    j = jlog.Journal(d)
    j.start(1)
    q = _mkq(journal=j.append)
    rng = np.random.default_rng(3)
    tail = jlog.JournalTail(d)
    _drive(q, rng, 10)
    tail.poll()
    q.checkpoint(meta=None)                # compacts under the tail
    _drive(q, rng, 10)
    tail.poll()
    rq, _meta = tail.finish()
    assert rq.state_digest() == q.state_digest()
    j.close()


# -- leader lease ----------------------------------------------------


def test_lease_acquire_renew_depose(tmp_path):
    lease = LeaderLease(str(tmp_path), timeout_s=0.3)
    e1 = lease.try_acquire("a")
    assert e1 == 1
    # live lease blocks a second owner, not the holder
    assert lease.try_acquire("b") is None
    assert lease.renew("a", e1)
    time.sleep(0.35)
    e2 = lease.try_acquire("b")
    assert e2 == 2
    assert lease.renew("a", e1) is False   # deposed by higher epoch
    assert lease.renew("b", e2)


def test_lease_corrupt_read_is_unknown_then_floor(tmp_path):
    lease = LeaderLease(str(tmp_path), timeout_s=10.0)
    lease.try_acquire("a")
    with chaos.inject(chaos.plan_from_spec(
            "seed=3;corrupt:fleet.ha.lease=@0+1")) as plan:
        sb = Standby(str(tmp_path), "b", lease_timeout_s=10.0)
        # first rotten read: UNKNOWN, no promotion
        assert sb._should_promote() is False
        # second consecutive rotten read: rot at rest — promote
        assert sb._should_promote() is True
        assert plan.fired("corrupt", "fleet.ha.lease") == 2


def test_epoch_collision_drill_recovers(tmp_path):
    """The double-leader drill: an io fault at epoch mint re-mints a
    stale (already-journaled) epoch; the O_EXCL segment is the
    backstop and election recovers above the collision."""
    d = str(tmp_path)
    a = become_leader(d, "a", lease_timeout_s=0.2)
    a.journal.append("cphase", {"rid": "x", "phase": "any"})
    a.journal.close()
    time.sleep(0.25)
    with obs.session() as sess, \
            chaos.inject(chaos.plan_from_spec(
                "seed=7;io:fleet.ha.epoch=@0")) as plan:
        b = become_leader(d, "b", lease_timeout_s=0.2)
        assert plan.fired("io", "fleet.ha.epoch") == 1
    assert b.epoch > a.epoch
    snap = sess.registry.snapshot()
    assert snap["counters"].get(
        "fleet.leader.epoch_collisions", 0) >= 1
    b.close()


# -- in-process failover ---------------------------------------------


def _coord(store, ctx, **kw):
    return Coordinator(str(store), lease_s=5.0, reap_interval_s=0.05,
                       ha=ctx, **kw)


def test_failover_preserves_inflight_request(tmp_path):
    d = str(tmp_path / "ha")
    store = tmp_path / "store"
    ctx = become_leader(d, "c0", lease_timeout_s=0.5)
    coord = _coord(store, ctx)
    client = RpcClient(coord.addr)
    try:
        client.call("hello", {"engine": "e0", "role": "both"})
        reply, _ = client.call("submit", {"prompt": [1, 2, 3],
                                          "n_new": 4})
        rid = reply["rid"]
        # leader "dies": its reaper (the renewal heartbeat) stops
        coord._stop.set()
        sb = Standby(d, "c1", lease_timeout_s=0.5, poll_s=0.02)
        t0 = time.monotonic()
        ctx2 = sb.run_until_leader()
        assert time.monotonic() - t0 < 1.0   # < 2x lease timeout
        coord2 = _coord(store, ctx2)
        try:
            assert coord2.epoch > coord.epoch
            assert coord2.queue.pending() == 1
            assert coord2._phase.get(rid) == "any"
            # the deposed leader fences every mutation...
            coord._deposed = True
            with pytest.raises(RpcError) as ei:
                client.call("submit", {"prompt": [9], "n_new": 1})
            assert ei.value.etype == "DeposedError"
            # ...and a lease-resolving client lands on the successor
            lc = LeaderClient(d, fallback_addr=coord.addr,
                              resolve_timeout_s=5.0)
            try:
                stats, _ = lc.call("fleet_stats")
                assert stats["epoch"] == coord2.epoch
                got, _ = lc.call("request", {"rid": rid})
                assert got["known"] and got["state"] == "queued"
            finally:
                lc.close()
        finally:
            coord2.shutdown()
            ctx2.close()
    finally:
        client.close()
        coord.shutdown()
        ctx.close()


def test_takeover_snapshot_supersedes_stale_appends(tmp_path):
    """A zombie predecessor appending after the successor's takeover
    snapshot cannot reach the NEXT replay: its records sort into an
    old-epoch segment below the snapshot."""
    d = str(tmp_path / "ha")
    store = tmp_path / "store"
    ctx = become_leader(d, "c0", lease_timeout_s=0.4)
    coord = _coord(store, ctx)
    rid = coord.submit(np.asarray([1, 2], np.int32), 3)
    coord._stop.set()
    time.sleep(0.45)
    ctx2 = become_leader(d, "c1", lease_timeout_s=0.4)
    coord2 = _coord(store, ctx2)    # writes the takeover snapshot
    # zombie writes AFTER the takeover — a stale submit-like record
    ctx.journal.append("cphase", {"rid": "zombie", "phase": "any"})
    coord2._stop.set()
    time.sleep(0.45)
    ctx3 = become_leader(d, "c2", lease_timeout_s=0.4)
    assert rid in ctx3.queue._requests
    assert "zombie" not in ctx3.meta.phases
    coord.shutdown(); coord2.shutdown()
    ctx.close(); ctx2.close(); ctx3.close()


# -- elastic roster --------------------------------------------------


def test_authenticated_join(tmp_path):
    coord = Coordinator(str(tmp_path), reap_interval_s=0.1,
                        join_token="sekrit")
    client = RpcClient(coord.addr)
    try:
        with obs.session() as sess:
            with pytest.raises(RpcError) as ei:
                client.call("hello", {"engine": "e0", "role": "both",
                                      "token": "wrong"})
            assert ei.value.etype == "PermissionError"
            reply, _ = client.call("hello", {
                "engine": "e0", "role": "both", "token": "sekrit"})
            assert reply["lease_s"] == coord.queue.lease_s
        snap = sess.registry.snapshot()
        assert snap["counters"]["fleet.roster.join_denied"] == 1
        assert snap["counters"]["fleet.roster.joins"] == 1
    finally:
        client.close()
        coord.shutdown()


def test_retire_drains_per_engine(tmp_path):
    coord = Coordinator(str(tmp_path), reap_interval_s=0.1)
    client = RpcClient(coord.addr)
    try:
        client.call("hello", {"engine": "e0", "role": "both"})
        client.call("hello", {"engine": "e1", "role": "both"})
        coord.submit(np.asarray([1, 2, 3], np.int32), 2)
        r, _ = client.call("claim", {"engine": "e0"})
        assert r["req"] is not None
        rid = r["req"]["rid"]
        reply, _ = client.call("retire", {"engine": "e1"})
        assert reply["retired"]
        # retired with an empty plate: out immediately, even though
        # the fleet still has work in flight
        d1, _ = client.call("drained", {"engine": "e1"})
        assert d1["drained"] is True
        # a retired engine gets no further claims
        c1, _ = client.call("claim", {"engine": "e1"})
        assert c1["req"] is None and c1["denied"] == "retired"
        # the working engine still drains normally
        d0, _ = client.call("drained", {"engine": "e0"})
        assert d0["drained"] is False
        client.call("complete", {"engine": "e0", "rid": rid,
                                 "seq": r["req"]["claim_seq"],
                                 "tokens": [5, 6]})
        d0, _ = client.call("drained", {"engine": "e0"})
        assert d0["drained"] is True
    finally:
        client.close()
        coord.shutdown()


def test_rejoin_after_failover_unknown_denial():
    """The RemoteQueue re-hello hook: a claim denied ``unknown``
    (failover successor never met this engine) triggers exactly one
    re-registration and the next claim succeeds."""
    from icikit.fleet.roles import RemoteQueue

    class FakeClient:
        def __init__(self):
            self.known = False
            self.calls = []

        def call(self, op, msg, blobs=()):
            self.calls.append(op)
            if op == "hello":
                self.known = True
                return {"ok": True, "lease_s": 5.0, "epoch": 2}, ()
            if op == "claim":
                if not self.known:
                    return {"ok": True, "req": None,
                            "denied": "unknown"}, ()
                return {"ok": True, "req": None}, ()
            raise AssertionError(op)

    c = FakeClient()
    hellos = []
    q = RemoteQueue(c, "e0", hello=lambda: (
        hellos.append(1), c.call("hello", {}))[-1])
    assert q.claim() is None
    assert hellos == [1]
    assert q.claim() is None
    assert hellos == [1]       # no re-hello once known
