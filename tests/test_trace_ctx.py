"""Request-scoped tracing (`icikit.obs.trace_ctx`): one async span
tree per request — whole on clean runs, continuous across dead-engine
reissue (ONE tree, an explicit ``reissued_from`` edge, no orphan
spans), fenced against stale engines, and invisible to the served
tokens (tracing on ≡ tracing off, bitwise)."""

import time

import jax
import numpy as np
import pytest

from icikit import chaos, obs
from icikit.obs import trace_ctx
from icikit.models.transformer import (
    TransformerConfig,
    init_params,
)
from icikit.models.transformer.model import make_model_mesh
from icikit.serve import Engine, RequestQueue, ServeConfig

CFG = TransformerConfig(vocab=61, d_model=32, n_heads=2, d_head=8,
                        d_ff=64, n_layers=2, max_seq=64,
                        compute_dtype="float32")


def _setup(n=2, seed=1, **over):
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, CFG.vocab, (8,)).astype(np.int32)
               for _ in range(n)]
    sv = dict(max_rows=2, block_size=4, n_blocks=32, max_prompt=16,
              max_new=16)
    sv.update(over)
    return mesh, params, ServeConfig(**sv), prompts


# -- async-span plumbing (tracer + chrome) --------------------------

def test_async_events_validate_across_threads():
    """The satellite contract: an async span may open on one thread
    track and close on another — the validator pairs by (cat, id),
    not by tid."""
    with obs.session(metrics=False) as s:
        s.trace.async_event("b", "x", "c", "id-1")
        import threading
        t = threading.Thread(
            target=lambda: s.trace.async_event("e", "x", "c", "id-1"))
        t.start()
        t.join()
    events = s.trace.snapshot()
    bs = [e for e in events if e["ph"] == "b"]
    es = [e for e in events if e["ph"] == "e"]
    assert bs[0]["tid"] != es[0]["tid"]      # genuinely cross-track
    assert obs.validate_trace(events) == []


def test_validator_catches_async_problems():
    base = {"pid": 1, "tid": 1, "ts": 0}
    assert any("unclosed b" in p for p in obs.validate_trace(
        [{"ph": "b", "name": "x", "cat": "c", "id": 1, **base}]))
    assert any("no open b" in p for p in obs.validate_trace(
        [{"ph": "e", "name": "x", "cat": "c", "id": 1, **base}]))
    assert any("missing cat/id" in p for p in obs.validate_trace(
        [{"ph": "b", "name": "x", **base}]))
    # LIFO per id: e naming other than the innermost open b
    assert any("nesting violation" in p for p in obs.validate_trace(
        [{"ph": "b", "name": "a", "cat": "c", "id": 1, **base},
         {"ph": "b", "name": "b", "cat": "c", "id": 1,
          "pid": 1, "tid": 1, "ts": 1},
         {"ph": "e", "name": "a", "cat": "c", "id": 1,
          "pid": 1, "tid": 1, "ts": 2}]))
    # distinct ids do not interleave-violate
    assert obs.validate_trace(
        [{"ph": "b", "name": "a", "cat": "c", "id": 1, **base},
         {"ph": "b", "name": "b", "cat": "c", "id": 2,
          "pid": 1, "tid": 1, "ts": 1},
         {"ph": "e", "name": "a", "cat": "c", "id": 1,
          "pid": 1, "tid": 1, "ts": 2},
         {"ph": "e", "name": "b", "cat": "c", "id": 2,
          "pid": 1, "tid": 1, "ts": 3}]) == []


def test_export_closes_dangling_async_spans(tmp_path):
    with obs.session(metrics=False) as s:
        s.trace.async_event("b", "req", "c", "id-9")
        s.trace.async_event("b", "attempt", "c", "id-9")
    raw = s.trace.snapshot()
    assert any("unclosed b" in p for p in obs.validate_trace(raw))
    path = tmp_path / "t.json"
    obs.export_trace(str(path), raw)
    assert obs.validate_trace(str(path)) == []
    import json
    evs = json.loads(path.read_text())["traceEvents"]
    synth = [e for e in evs if e["ph"] == "e"
             and e.get("args", {}).get("closed_by") == "export"]
    # LIFO: the inner span closes first
    assert [e["name"] for e in synth] == ["attempt", "req"]


# -- TraceCtx unit behavior -----------------------------------------

def test_ctx_disabled_is_noop_and_stale_seq_fences():
    ctx = trace_ctx.mint("r0")
    ctx.open("serve.req")          # tracing off: no state, no events
    assert ctx._open == []
    with obs.session(metrics=False) as s:
        ctx.begin_attempt(1)
        ctx.instant("serve.req.step", seq=1, step=0)
        ctx.instant("serve.req.step", seq=7, step=1)   # stale: no-op
        with ctx.span("serve.req.prefill.chunk", seq=7):
            pass                                       # stale: no-op
        ctx.end_attempt()
    names = [(e["ph"], e["name"]) for e in s.trace.snapshot()
             if e.get("cat") == trace_ctx.CAT]
    assert names == [("b", "serve.req.attempt"),
                     ("n", "serve.req.step"),
                     ("e", "serve.req.attempt")]


def test_ctx_close_through_nested(tmp_path):
    """A terminal edge arriving while an inner span is open closes
    through it LIFO — the validator must stay satisfied."""
    ctx = trace_ctx.mint("r0")
    with obs.session(metrics=False) as s:
        ctx.open("serve.req")
        ctx.begin_attempt(1)
        ctx.open("serve.req.prefill.chunk", seq=1)
        ctx.close("serve.req", state="done")
    events = s.trace.snapshot()
    assert obs.validate_trace(events) == []
    es = [e for e in events if e["ph"] == "e"]
    assert [e["name"] for e in es] == ["serve.req.prefill.chunk",
                                      "serve.req.attempt",
                                      "serve.req"]
    assert es[0]["args"]["closed_by"] == "serve.req"


# -- engine integration ---------------------------------------------

def test_clean_run_yields_whole_request_trees():
    mesh, params, sv, prompts = _setup(n=3, speculate_k=3,
                                       prefill_chunk=4)
    with obs.session() as s:
        eng = Engine(params, mesh, CFG, sv)
        rids = [eng.submit(p, 10) for p in prompts]
        eng.run()
        events = s.trace.snapshot()
    assert obs.validate_trace(events) == []
    trees = trace_ctx.request_trees(events)
    assert len(trees) == len(rids)
    for evs in trees.values():
        names = [(e["ph"], e["name"]) for e in evs]
        # root opens first, closes last; queue-wait precedes attempt
        assert names[0] == ("b", "serve.req")
        assert names[1] == ("b", "serve.req.queued")
        assert names[-1] == ("e", "serve.req")
        flat = [n for _, n in names]
        assert "serve.req.prefill.chunk" in flat
        assert "serve.req.first_token" in flat
        assert "serve.req.step" in flat
        # balanced within the tree — no orphans, no export synthetics
        assert sum(1 for ph, _ in names if ph == "b") == \
            sum(1 for ph, _ in names if ph == "e")
        assert not any(e.get("args", {}).get("closed_by") == "export"
                       for e in evs)
        # speculation stats ride the step instants (k=3: the step IS
        # the verify window)
        steps = [e for e in evs if e["name"] == "serve.req.step"]
        assert all("accepted" in e["args"] for e in steps)
    # the co-batch roster joins engine steps to request trees
    rosters = [e["args"]["roster"] for e in events
               if e.get("name") == "serve.engine.step"
               and e["ph"] == "B" and e["args"]["rows"]]
    assert rosters and all(
        set(r) <= set(trees) for r in rosters)


def test_dead_engine_reissue_one_tree_with_edge():
    """The chaos continuity pin: an engine dies mid-serve, leases
    expire, a second engine completes — each request has ONE tree,
    its second attempt carries reissued_from, the reap closed the
    abandoned spans (no orphans), and the whole trace validates."""
    mesh, params, sv, prompts = _setup()
    q = RequestQueue(lease_s=0.05)
    plan = chaos.FaultPlan(schedule={"die:serve.step": (0,)})
    with obs.session() as s:
        eng1 = Engine(params, mesh, CFG, sv, queue=q)
        rids = [eng1.submit(p, 10) for p in prompts]
        with chaos.inject(plan):
            with pytest.raises(chaos.InjectedDeath):
                eng1.run()
            time.sleep(0.06)
            eng2 = Engine(params, mesh, CFG, sv, queue=q)
            eng2.run()
        events = s.trace.snapshot()
    assert q.n_reissues == len(rids)
    assert obs.validate_trace(events) == []     # no orphan spans
    trees = trace_ctx.request_trees(events)
    assert len(trees) == len(rids)              # ONE tree per request
    for evs in trees.values():
        attempts = [e for e in evs if e["ph"] == "b"
                    and e["name"] == "serve.req.attempt"]
        assert [a["args"]["attempt"] for a in attempts] == [1, 2]
        # the explicit continuity edge: attempt 2 names the claim
        # generation the reap abandoned
        assert attempts[1]["args"]["reissued_from"] == \
            attempts[0]["args"]["claim_seq"]
        reaps = [e for e in evs if e["name"] == "serve.req.reissued"]
        assert len(reaps) == 1
        # the dead engine's spans were closed BY THE REAP, not left
        # dangling for the exporter
        assert any(e["ph"] == "e"
                   and e.get("args", {}).get("closed_by")
                   == "lease_reaped" for e in evs)
        assert evs[-1]["name"] == "serve.req" and evs[-1]["ph"] == "e"


def test_tracing_on_off_bitwise_identical_tokens():
    """Tracing must never touch the served bytes: the same workload
    (tree speculation armed — the densest instrumentation path)
    commits identical tokens with tracing on and off."""
    mesh, params, sv, prompts = _setup(n=3, speculate_k=3,
                                       tree_branch=2, prefill_chunk=4)

    def serve():
        eng = Engine(params, mesh, CFG, sv)
        rids = [eng.submit(p, 10, seed=i, temperature=0.5)
                for i, p in enumerate(prompts)]
        eng.run()
        return [tuple(eng.queue.request(r).tokens) for r in rids]

    base = serve()                       # tracing off
    with obs.session() as s:
        traced = serve()                 # tracing + metrics on
        assert obs.validate_trace(s.trace.snapshot()) == []
    assert traced == base


def test_ctx_ops_disabled_allocate_nothing():
    """The zero-overhead-disabled re-assertion, trace-ctx ops and the
    speculation counter sites included (the tracemalloc harness from
    test_obs, pointed at the new probes)."""
    import tracemalloc
    ctx = trace_ctx.mint("r0")

    def hot():
        for _ in range(300):
            ctx.instant("serve.req.step", seq=1, step=0, accepted=1)
            with ctx.span("serve.req.prefill.chunk", seq=1):
                pass
            obs.count("serve.spec.tree.draft_accepted", 3)
            obs.count("serve.spec.tree.primary", 2)
            obs.count("serve.spec.tree.sideways", 1)

    hot()   # warm lazy internals
    tracemalloc.start()
    hot()
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert peak < 4096, f"disabled trace-ctx path allocated {peak} B"
