"""Reduce-to-root family: the MPI_Reduce analog (main.cc:445,
psort.cc:652). Binomial tree vs the XLA baseline, all ops, any root,
non-power-of-2 meshes."""

import numpy as np
import pytest

from icikit.parallel import REDUCE_ALGORITHMS, reduce_to_root
from icikit.utils.mesh import make_mesh, shard_along

import jax.numpy as jnp


def _data(p, m=5, dtype=np.float32, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(-50, 50, size=(p, m)).astype(dtype)


_NP_OPS = {"sum": np.sum, "max": np.max, "min": np.min}


@pytest.mark.parametrize("algorithm", REDUCE_ALGORITHMS)
@pytest.mark.parametrize("p", [2, 3, 5, 8])
@pytest.mark.parametrize("op", ["sum", "max", "min"])
def test_reduce_matches_numpy(algorithm, p, op):
    mesh = make_mesh(p)
    data = _data(p)
    x = shard_along(jnp.asarray(data), mesh)
    out = np.asarray(reduce_to_root(x, mesh, algorithm=algorithm, op=op))
    np.testing.assert_array_equal(out[0], _NP_OPS[op](data, axis=0))
    assert not np.any(out[1:]), "non-root rows must be zero"


@pytest.mark.parametrize("algorithm", REDUCE_ALGORITHMS)
@pytest.mark.parametrize("root", [1, 3, 6])
def test_reduce_nonzero_root(algorithm, root):
    p = 7
    mesh = make_mesh(p)
    data = _data(p, seed=root)
    x = shard_along(jnp.asarray(data), mesh)
    out = np.asarray(reduce_to_root(x, mesh, algorithm=algorithm,
                                    op="max", root=root))
    np.testing.assert_array_equal(out[root], data.max(axis=0))
    mask = np.ones(p, bool)
    mask[root] = False
    assert not np.any(out[mask])


def test_reduce_timing_protocol_shape():
    # the reference's timing close: every rank contributes its wall
    # time, rank 0 reports the max (main.cc:443-449)
    p = 8
    mesh = make_mesh(p)
    times = np.abs(_data(p, m=1)).astype(np.float32)
    x = shard_along(jnp.asarray(times), mesh)
    out = np.asarray(reduce_to_root(x, mesh, op="max"))
    assert out[0, 0] == times.max()


def test_reduce_p1_identity():
    mesh = make_mesh(1)
    data = _data(1)
    x = shard_along(jnp.asarray(data), mesh)
    out = np.asarray(reduce_to_root(x, mesh, algorithm="binomial"))
    np.testing.assert_array_equal(out, data)
