"""icikit.analysis — framework + rule tests.

Covers the ISSUE-14 contract: golden findings on the seeded-violation
corpus (one violation per rule, each with a clean twin that must stay
quiet), suppression-comment and baseline round trips, both directions
of the migrated Makefile greps, parity pins (each ported rule
reproduces its predecessor's clean verdict on the real tree), the
chaos-site helpers that were review-hardened twice without direct
coverage, and the CLI's --json shape + --self-check drill.
"""

from __future__ import annotations

import json
import os

import pytest

from icikit.analysis import Project, run_rules
from icikit.analysis import baseline as bl
from icikit.analysis.cli import main as cli_main
from icikit.analysis.core import Finding, repo_root
from icikit.analysis.rules.chaos_site import (
    ENV_ENTRY,
    collapse_holes,
    local_probes,
    scan_entries,
)

HERE = os.path.dirname(os.path.abspath(__file__))
CORPUS = os.path.join(HERE, "analysis_corpus")
BAD = os.path.join(CORPUS, "bad")
CLEAN = os.path.join(CORPUS, "clean")

# every static rule (quant-arena is runtime: it checks the REAL
# package's arenas/jaxprs regardless of project root, so the corpus
# cannot seed it — its parity pin below covers it)
STATIC_RULES = ["serve-key", "serve-clock", "obs-print", "tree-accept",
                "obs-catalog", "host-sync", "lock-discipline",
                "chaos-site", "fleet-control-plane", "journal-discipline"]

# rule -> the seeded violation(s) in the bad twin (most rules seed
# exactly one; fleet-control-plane pins one per r19 plane module too)
GOLDEN = {
    "serve-key": [("icikit/serve/unkeyed.py", 4)],
    "serve-clock": [("icikit/serve/wallclock.py", 4)],
    "obs-print": [("icikit/leak.py", 4)],
    "tree-accept": [("icikit/models/transformer/speculative.py", 9)],
    "obs-catalog": [("icikit/emit.py", 4)],
    "host-sync": [("icikit/serve/engine.py", 14)],
    "lock-discipline": [("icikit/serve/locked.py", 15)],
    "chaos-site": [("tests/drill.py", 4)],
    "fleet-control-plane": [("icikit/fleet/coordinator.py", 4),
                            ("icikit/fleet/telemetry.py", 5),
                            ("icikit/obs/aggregate.py", 5)],
    "journal-discipline": [("icikit/serve/scheduler.py", 22)],
}


def _findings(root, rules):
    return run_rules(Project(root), rules)


# -- golden corpus ---------------------------------------------------

@pytest.mark.parametrize("rule", sorted(GOLDEN))
def test_seeded_violation_fires(rule):
    want = sorted(GOLDEN[rule])
    got = sorted((f.path, f.line) for f in _findings(BAD, [rule]))
    assert got == want, (
        f"{rule}: expected exactly the seeded violations "
        f"{want}, got {got}")


@pytest.mark.parametrize("rule", sorted(GOLDEN))
def test_clean_twin_quiet(rule):
    got = _findings(CLEAN, [rule])
    assert got == [], (
        f"{rule}: clean twin should be finding-free, got "
        f"{[f.render() for f in got]}")


def test_all_static_rules_together_on_bad():
    got = {(f.rule, f.path, f.line)
           for f in _findings(BAD, STATIC_RULES)}
    want = {(r, p, ln) for r, hits in GOLDEN.items()
            for p, ln in hits}
    assert got == want


# -- suppressions ----------------------------------------------------

def _mini(tmp_path, rel, text):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(text)
    return str(tmp_path)


def test_suppression_comment_silences_named_rule(tmp_path):
    root = _mini(tmp_path, "icikit/serve/x.py",
                 "import time\n"
                 "t = time.time()  # icikit-lint: off[serve-clock]\n")
    assert _findings(root, ["serve-clock"]) == []


def test_suppression_is_per_rule(tmp_path):
    # the off[] names serve-clock only: serve-key still fires on the
    # same line
    root = _mini(
        tmp_path, "icikit/serve/x.py",
        "import numpy as np, time\n"
        "t = np.random.rand() * time.time()"
        "  # icikit-lint: off[serve-clock]\n")
    assert [f.rule for f in _findings(
        root, ["serve-clock", "serve-key"])] == ["serve-key"]


def test_bare_off_silences_everything(tmp_path):
    root = _mini(tmp_path, "icikit/serve/x.py",
                 "import numpy as np, time\n"
                 "t = np.random.rand() * time.time()"
                 "  # icikit-lint: off\n")
    assert _findings(root, ["serve-clock", "serve-key"]) == []


def test_unsuppressed_twin_fires(tmp_path):
    root = _mini(tmp_path, "icikit/serve/x.py",
                 "import time\nt = time.time()\n")
    assert [f.rule for f in _findings(root, ["serve-clock"])] \
        == ["serve-clock"]


# -- baseline round trip ---------------------------------------------

def test_baseline_round_trip(tmp_path):
    root = _mini(tmp_path, "icikit/serve/x.py",
                 "import time\nt = time.time()\n")
    found = _findings(root, ["serve-clock"])
    assert len(found) == 1
    path = str(tmp_path / "baseline.json")
    bl.write(path, found)
    entries = bl.load(path)
    fresh, grandfathered, stale = bl.split(found, entries)
    assert fresh == [] and len(grandfathered) == 1 and stale == []
    # dropping the entry re-arms the finding
    fresh2, _, _ = bl.split(found, [])
    assert fresh2 == found
    # a fixed finding turns its entry stale (reported, not fatal)
    _, _, stale2 = bl.split([], entries)
    assert len(stale2) == 1


def test_baseline_count_caps_absorption(tmp_path):
    """An entry absorbs at most its count: a NEW violation that
    renders the same message as a grandfathered one must come out
    unbaselined, not ride the exemption."""
    root = _mini(tmp_path, "icikit/serve/x.py",
                 "import time\n"
                 "t = time.time()\n"
                 "u = time.time()\n")
    found = _findings(root, ["serve-clock"])
    assert len(found) == 2 and found[0].msg == found[1].msg
    entries = [{"rule": "serve-clock", "path": "icikit/serve/x.py",
                "msg": found[0].msg, "note": "one grandfathered"}]
    fresh, grandfathered, stale = bl.split(found, entries)
    assert len(fresh) == 1 and len(grandfathered) == 1
    assert stale == []
    # count=2 absorbs both; an unconsumed budget turns the entry stale
    entries[0]["count"] = 2
    fresh, grandfathered, stale = bl.split(found, entries)
    assert fresh == [] and len(grandfathered) == 2 and stale == []
    fresh, grandfathered, stale = bl.split(found[:1], entries)
    assert fresh == [] and len(stale) == 1


def test_baseline_requires_note(tmp_path):
    path = tmp_path / "baseline.json"
    path.write_text(json.dumps(
        [{"rule": "serve-clock", "path": "icikit/serve/x.py",
          "msg": "wall clock", "note": "  "}]))
    with pytest.raises(ValueError, match="justification"):
        bl.load(str(path))


def test_committed_baseline_entries_all_match_live_findings():
    """Every entry in the real tools/analysis_baseline.json matches a
    live finding — a stale entry means the code was fixed and the
    baseline should shed it."""
    root = repo_root()
    entries = bl.load(os.path.join(root, bl.DEFAULT_BASELINE))
    found = _findings(root, ["lock-discipline", "host-sync"])
    _, _, stale = bl.split(found, entries)
    assert stale == [], [e["msg"] for e in stale]


# -- migrated Makefile greps: both directions ------------------------

def test_obs_print_seeded_fails_and_obs_is_exempt(tmp_path):
    root = _mini(tmp_path, "icikit/x.py",
                 "import json\nprint(json.dumps({}))\n")
    _mini(tmp_path, "icikit/obs/y.py",
          "import json\nprint(json.dumps({}))\n")
    got = _findings(root, ["obs-print"])
    assert [(f.path, f.line) for f in got] == [("icikit/x.py", 2)]


def test_serve_clock_only_polices_serve_tree(tmp_path):
    root = _mini(tmp_path, "icikit/serve/x.py",
                 "import time\nt = time.time()\n")
    _mini(tmp_path, "icikit/bench/y.py",
          "import time\nt = time.time()\n")
    got = _findings(root, ["serve-clock"])
    assert [(f.path, f.line) for f in got] \
        == [("icikit/serve/x.py", 2)]


# -- parity pins: ported rules on the real tree ----------------------

@pytest.mark.parametrize("rule", ["serve-key", "serve-clock",
                                  "obs-print", "tree-accept",
                                  "obs-catalog"])
def test_ported_rule_parity_on_real_tree(rule):
    """Each ported rule reproduces its predecessor's verdict on the
    real tree: the predecessors all pass today, so the port must
    report zero findings (modulo the committed baseline, which these
    rules have no entries in)."""
    got = _findings(repo_root(), [rule])
    assert got == [], [f.render() for f in got]


def test_chaos_site_parity_on_real_tree():
    got = _findings(repo_root(), ["chaos-site"])
    assert got == [], [f.render() for f in got]


@pytest.mark.slow
def test_quant_rule_parity_on_real_tree():
    """The runtime quant-arena port reproduces tools/quant_lint.py's
    passing verdict (slow: builds pools, runs a tiny engine)."""
    got = _findings(repo_root(), ["quant-arena"])
    assert got == [], [f.render() for f in got]


def test_corpus_is_excluded_from_real_walk():
    got = _findings(repo_root(), STATIC_RULES)
    leaked = [f for f in got
              if f.path.startswith("tests/analysis_corpus")]
    assert leaked == [], [f.render() for f in leaked]


def test_new_rules_gate_green_on_real_tree_with_baseline():
    """The acceptance bar: zero UNBASELINED host-sync /
    lock-discipline findings post-PR."""
    root = repo_root()
    found = _findings(root, ["host-sync", "lock-discipline"])
    entries = bl.load(os.path.join(root, bl.DEFAULT_BASELINE))
    fresh, _, _ = bl.split(found, entries)
    assert fresh == [], [f.render() for f in fresh]


# -- chaos-site helpers (review-hardened, now unit-covered) ----------

def test_collapse_holes():
    assert collapse_holes("solitaire.worker.{w}") \
        == "solitaire.worker.*"
    assert collapse_holes("a.{i}.b.{j}") == "a.*.b.*"
    assert collapse_holes("serve.kv.page") == "serve.kv.page"


def test_env_entry_matches_makefile_spec_form():
    """The PR 10 regression: the env-spec glob is followed by
    '=value', not a closing quote — the original ENTRY regex matched
    the Makefile's own spec form NEVER."""
    line = 'ICIKIT_CHAOS="seed=0;corrupt:serve.kv.page=@0"'
    assert ENV_ENTRY.findall(line) == [("corrupt", "serve.kv.page")]


def test_scan_entries_quoted_and_env_forms():
    text = ('plan = {"die:solitaire.worker.{w}": 1}\n'
            'env = "seed=1;delay:serve.step=0.1"\n')
    assert scan_entries(text) == [
        (1, "die", "solitaire.worker.*"),
        (2, "delay", "serve.step"),
    ]


def test_scan_entries_honors_legacy_off_marker():
    text = 'bad = "die:nope.nope"  # chaos-site-lint: off\n'
    assert scan_entries(text) == []


def test_local_probes_collapse():
    text = 'chaos.maybe_die(f"w.{i}")\nfires("delay", "x")\n'
    assert local_probes(text) == {"w.*", "x"}


# -- lock-discipline specifics ---------------------------------------

def test_two_lock_blocking_call_flagged(tmp_path):
    root = _mini(tmp_path, "icikit/serve/d.py",
                 "class D:\n"
                 "    def f(self, ev):\n"
                 "        with self._lock:\n"
                 "            with self._page_lock:\n"
                 "                ev.wait()\n")
    got = _findings(root, ["lock-discipline"])
    assert len(got) == 1 and "two locks" in got[0].msg


def test_single_lock_plain_wait_not_flagged(tmp_path):
    # .wait() is only banned at two locks; under ONE lock it is the
    # condition-variable idiom
    root = _mini(tmp_path, "icikit/serve/d.py",
                 "class D:\n"
                 "    def f(self, ev):\n"
                 "        with self._lock:\n"
                 "            ev.wait()\n")
    assert _findings(root, ["lock-discipline"]) == []


def test_lock_held_helper_propagation(tmp_path):
    root = _mini(tmp_path, "icikit/serve/h.py",
                 "import time\n"
                 "class H:\n"
                 "    def _inner(self):\n"
                 "        time.sleep(0.1)\n"
                 "    def outer(self):\n"
                 "        with self._lock:\n"
                 "            self._inner()\n")
    got = _findings(root, ["lock-discipline"])
    assert [(f.path, f.line) for f in got] \
        == [("icikit/serve/h.py", 4)]
    assert "lock-held helper" in got[0].msg


# -- host-sync specifics ---------------------------------------------

def test_host_sync_iteration_over_device_always_flagged(tmp_path):
    root = _mini(tmp_path, "icikit/serve/engine.py",
                 "class E:\n"
                 "    def _step(self):\n"
                 "        outs = self._step_fns[0](self.p)\n"
                 "        for t in outs:\n"
                 "            self.emit(t)\n")
    got = _findings(root, ["host-sync"])
    assert len(got) == 1 and "iteration" in got[0].msg


def test_host_sync_nonfence_scope_flags_top_level_sync(tmp_path):
    # run() is a scoped NON-fence function: even a loop-free sync
    # belongs at a documented fence
    root = _mini(tmp_path, "icikit/serve/engine.py",
                 "import numpy as np\n"
                 "class E:\n"
                 "    def run(self):\n"
                 "        outs = self._step_fns[0](self.p)\n"
                 "        return np.asarray(outs)\n")
    got = _findings(root, ["host-sync"])
    assert len(got) == 1 and "documented fences" in got[0].msg


def test_host_sync_device_get_batch_is_clean(tmp_path):
    # the prescribed fix shape: one batched device_get, then host math
    root = _mini(tmp_path, "icikit/serve/engine.py",
                 "import jax\n"
                 "class E:\n"
                 "    def _step(self):\n"
                 "        pend = []\n"
                 "        outs = self._step_fns[0](self.p)\n"
                 "        pend.append(outs)\n"
                 "        for o in jax.device_get(pend):\n"
                 "            x = float(o)\n"
                 "        return x\n")
    assert _findings(root, ["host-sync"]) == []


def test_host_sync_container_of_device_values_flagged(tmp_path):
    # append device values, then sync per item in the drain loop —
    # the r13 drain-at-fence regression shape
    root = _mini(tmp_path, "icikit/serve/engine.py",
                 "class E:\n"
                 "    def _step(self):\n"
                 "        pend = []\n"
                 "        outs = self._step_fns[0](self.p)\n"
                 "        pend.append(outs)\n"
                 "        acc = 0.0\n"
                 "        for o in pend:\n"
                 "            acc += float(o)\n"
                 "        return acc\n")
    got = _findings(root, ["host-sync"])
    assert len(got) == 1 and got[0].line == 8


def test_makefile_finding_stays_a_chaos_finding(tmp_path):
    # a Makefile finding routes through the suppression lookup like
    # any other — and must NOT drag the (unparsable-as-python)
    # Makefile into the parse-error sweep
    (tmp_path / "Makefile").write_text(
        'drill:\n\tICIKIT_CHAOS='
        '"seed=0;die:not.a.site=@0" run\n')  # chaos-site-lint: off
    got = _findings(str(tmp_path), ["chaos-site"])
    assert [(f.rule, f.path) for f in got] \
        == [("chaos-site", "Makefile")]


def test_host_sync_while_test_is_per_iteration(tmp_path):
    # a while CONDITION re-evaluates every pass: a sync in it is a
    # per-iteration sync even at the top of a fence function
    root = _mini(tmp_path, "icikit/serve/engine.py",
                 "class E:\n"
                 "    def _step(self):\n"
                 "        outs = self._step_fns[0](self.p)\n"
                 "        while float(outs) > 0:\n"
                 "            outs = self._step_fns[0](self.p)\n")
    got = _findings(root, ["host-sync"])
    assert len(got) == 1 and got[0].line == 4


def test_cli_json_overflow_finding_not_marked_baselined(tmp_path):
    # count-capped entry: the overflow (fresh) finding shares the
    # baseline KEY with the absorbed one but must report
    # baselined:false in the machine-readable output
    root = _mini(tmp_path, "icikit/serve/x.py",
                 "import time\n"
                 "t = time.time()\n"
                 "u = time.time()\n")
    found = _findings(root, ["serve-clock"])
    blpath = tmp_path / "bl.json"
    blpath.write_text(json.dumps(
        [{"rule": "serve-clock", "path": "icikit/serve/x.py",
          "msg": found[0].msg, "count": 1, "note": "one only"}]))
    out = tmp_path / "report.json"
    rc = cli_main(["--root", root, "--rules", "serve-clock",
                   "--gate", "--baseline", str(blpath),
                   "--json", str(out)])
    assert rc == 1
    payload = json.loads(out.read_text())
    flags = {f["line"]: f["baselined"] for f in payload["findings"]}
    assert flags == {2: True, 3: False}
    assert payload["counts"]["unbaselined"] == 1


# -- parse errors ----------------------------------------------------

def test_parse_error_is_a_finding_not_a_crash(tmp_path):
    root = _mini(tmp_path, "icikit/serve/broken.py",
                 "def f(:\n")
    got = _findings(root, ["host-sync", "lock-discipline"])
    assert [f.rule for f in got] == ["parse-error"]


# -- CLI -------------------------------------------------------------

def test_cli_json_shape(tmp_path):
    out = tmp_path / "report.json"
    rc = cli_main(["--root", BAD, "--rules", "serve-clock",
                   "--json", str(out)])
    assert rc == 0          # findings without --gate exit 0
    payload = json.loads(out.read_text())
    assert payload["version"] == 1
    assert payload["rules"] == ["serve-clock"]
    assert payload["counts"]["findings"] == 1
    assert payload["counts"]["unbaselined"] == 1
    [f] = payload["findings"]
    assert f["rule"] == "serve-clock"
    assert f["path"] == "icikit/serve/wallclock.py"
    assert f["line"] == 4 and f["baselined"] is False
    assert set(f) == {"rule", "path", "line", "msg", "baselined"}


def test_cli_gate_fails_on_bad_and_passes_on_clean():
    assert cli_main(["--root", BAD, "--rules", "serve-clock",
                     "--gate"]) == 1
    assert cli_main(["--root", CLEAN, "--rules", "serve-clock",
                     "--gate"]) == 0


def test_cli_self_check_drill():
    """The seeded-violation drill proves every seedable rule can
    still fail the gate."""
    assert cli_main(["--root", CLEAN, "--rules", "serve-clock",
                     "--self-check"]) == 0


def test_cli_write_baseline_then_gate_green(tmp_path):
    blpath = tmp_path / "bl.json"
    assert cli_main(["--root", BAD, "--rules", "serve-clock",
                     "--write-baseline",
                     "--baseline", str(blpath)]) == 0
    assert cli_main(["--root", BAD, "--rules", "serve-clock",
                     "--gate", "--baseline", str(blpath)]) == 0


# -- backward-compat shims -------------------------------------------

@pytest.mark.parametrize("mod", ["serve_key_lint", "chaos_site_lint",
                                 "tree_accept_lint",
                                 "obs_catalog_lint"])
def test_tool_shims_still_pass(mod):
    # the old entry points (quant_lint is the slow runtime one —
    # exercised by make check) still exist and still pass
    import importlib.util
    path = os.path.join(repo_root(), "tools", f"{mod}.py")
    spec = importlib.util.spec_from_file_location(mod, path)
    shim = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(shim)
    assert shim.main() == 0
