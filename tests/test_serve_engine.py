"""Serving engine: token identity vs single-request generate, plus
admission / eviction / preemption mechanics.

The load-bearing property is the acceptance bar from ROADMAP item 1:
whatever the admission timing, co-batching, prompt-length mix,
speculative mode, or mesh, every request's output tokens are
bitwise what ``greedy_generate`` produces for that request alone.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from icikit.models.transformer import (
    TransformerConfig,
    greedy_generate,
    init_params,
)
from icikit.models.transformer.model import make_model_mesh
from icikit.serve import Engine, RequestQueue, ServeConfig

CFG = TransformerConfig(vocab=61, d_model=32, n_heads=4, d_head=8,
                        d_ff=64, n_layers=2, max_seq=64,
                        compute_dtype="float32")


def _baseline(cfg, prompt, n_new):
    """Single-request greedy reference on a dp=1/tp=1 mesh (tokens are
    mesh-independent — pinned by tests/test_decode.py)."""
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), cfg, mesh)
    out = greedy_generate(params, jnp.asarray(prompt)[None], mesh, cfg,
                          n_new)
    return np.asarray(out)[0, len(prompt):]


def _workload(cfg, lens, seed=1):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, cfg.vocab, (s,)).astype(np.int32)
            for s in lens]


def _engine(cfg=CFG, dp=1, tp=1, **over):
    mesh = make_model_mesh(dp=dp, tp=tp, sp=1)
    params = init_params(jax.random.key(0), cfg, mesh)
    sv = dict(max_rows=2, block_size=4, n_blocks=32, max_prompt=16,
              max_new=16)
    sv.update(over)
    return Engine(params, mesh, cfg, ServeConfig(**sv))


@pytest.mark.parametrize("speculate_k", [1, 3])
def test_mixed_lengths_staggered_admission_identity(speculate_k):
    """4 requests over 2 rows, three prompt lengths, staggered
    arrivals: every request's tokens match its solo baseline."""
    prompts = _workload(CFG, [5, 8, 11, 8])
    n_news = [6, 12, 9, 4]
    eng = _engine(speculate_k=speculate_k)
    t0 = time.monotonic()
    rids = [eng.submit(p, n, not_before=t0 + 0.01 * i)
            for i, (p, n) in enumerate(zip(prompts, n_news))]
    assert eng.run() == len(rids)
    for rid, p, n in zip(rids, prompts, n_news):
        req = eng.queue.request(rid)
        assert req.state == "done"
        np.testing.assert_array_equal(np.asarray(req.tokens),
                                      _baseline(CFG, p, n))


@pytest.mark.parametrize("dp,tp", [(2, 1), (1, 2), (2, 2)])
def test_identity_across_meshes(dp, tp):
    prompts = _workload(CFG, [6, 9, 6])
    eng = _engine(dp=dp, tp=tp, max_rows=2 * dp)
    rids = [eng.submit(p, 8) for p in prompts]
    eng.run()
    for rid, p in zip(rids, prompts):
        req = eng.queue.request(rid)
        assert req.state == "done"
        np.testing.assert_array_equal(np.asarray(req.tokens),
                                      _baseline(CFG, p, 8))


def test_eos_freezes_and_frees_the_row():
    """A request with eos_id stops at the first EOS token (inclusive)
    — the engine's output is the solo continuation truncated at EOS,
    and the freed row admits the next request."""
    [prompt] = _workload(CFG, [8], seed=3)
    base = _baseline(CFG, prompt, 12)
    eos = int(base[4])       # force an early stop at a real token
    upto = list(base).index(eos) + 1
    eng = _engine(max_rows=2)
    r1 = eng.submit(prompt, 12, eos_id=eos)
    r2 = eng.submit(prompt, 12)      # no EOS: runs to n_new
    eng.run()
    req1, req2 = eng.queue.request(r1), eng.queue.request(r2)
    np.testing.assert_array_equal(np.asarray(req1.tokens), base[:upto])
    np.testing.assert_array_equal(np.asarray(req2.tokens), base)
    assert req1.done_t <= req2.done_t


def test_single_token_request_finishes_at_prefill():
    [prompt] = _workload(CFG, [7], seed=4)
    eng = _engine()
    rid = eng.submit(prompt, 1)
    eng.run()
    req = eng.queue.request(rid)
    assert req.state == "done"
    np.testing.assert_array_equal(np.asarray(req.tokens),
                                  _baseline(CFG, prompt, 1))
    assert eng.pool.occupancy() == 0.0   # blocks returned


def test_pool_preemption_retries_to_completion():
    """A pool too small for two rows admits serially: the second
    request is preempted at admission (no retry burned), backs off,
    and completes with identical tokens once the first evicts."""
    prompts = _workload(CFG, [8, 8], seed=5)
    # one row's worst case needs ceil((8+12)/4)=5 blocks; give 7 so
    # both admit but cannot both extend to full length
    eng = _engine(n_blocks=7, max_prompt=8, max_new=12)
    rids = [eng.submit(p, 12, max_retries=0) for p in prompts]
    eng.run()
    pre = 0
    for rid, p in zip(rids, prompts):
        req = eng.queue.request(rid)
        assert req.state == "done"     # max_retries=0: preemption must
        pre += req.preempted           # not have consumed a retry
        np.testing.assert_array_equal(np.asarray(req.tokens),
                                      _baseline(CFG, p, 12))
    assert pre >= 1
    assert eng.pool.occupancy() == 0.0


def test_occupancy_and_slo_marks():
    prompts = _workload(CFG, [8, 8, 8, 8], seed=6)
    eng = _engine(max_rows=2)
    rids = [eng.submit(p, 8) for p in prompts]
    eng.run()
    assert 0.5 < eng.occupancy_mean() <= 1.0
    for rid in rids:
        slo = eng.queue.request(rid).slo()
        assert slo["ttft_ms"] >= slo["queue_wait_ms"] >= 0.0
        assert slo["tpot_ms"] > 0.0
        assert slo["n_tokens"] == 8


def test_queue_lease_expiry_reissues():
    """Scheduler-level dead-engine story: a claimed request whose
    lease is never renewed comes back on reap."""
    q = RequestQueue(lease_s=0.03)
    rid = q.submit(np.asarray([1, 2], np.int32), 4)
    req = q.claim()
    assert req.rid == rid and q.claim() is None
    assert q.reap_expired() == []          # lease still fresh
    time.sleep(0.04)
    assert q.reap_expired() == [rid]
    again = q.claim()
    assert again.rid == rid and again.attempts == 2


def test_queue_complete_is_idempotent():
    q = RequestQueue()
    rid = q.submit(np.asarray([1], np.int32), 2)
    q.claim()
    assert q.complete(rid, [5, 6]) is True
    assert q.complete(rid, [7, 8]) is False     # late duplicate
    assert q.request(rid).tokens == [5, 6]      # first commit won
    assert q.n_duplicate_commits == 1
    assert q.drained()


def test_queue_retry_backoff_then_fail():
    q = RequestQueue(backoff_s=0.01)
    rid = q.submit(np.asarray([1], np.int32), 2, max_retries=1)
    q.claim()
    assert q.fail(rid, RuntimeError("boom")) == "queued"
    assert q.claim() is None               # backoff gates visibility
    time.sleep(0.015)
    assert q.claim().rid == rid
    assert q.fail(rid, RuntimeError("boom2")) == "failed"
    assert rid in q.failed and "boom2" in q.failed[rid].error
    assert q.drained()


def test_stale_engine_cannot_double_queue_or_mutate():
    """A reaped lease fences the old claimant: its fail() is a stale
    no-op (no duplicate heap entry -> no double admission) and its
    late complete() cannot commit over the reissued attempt."""
    q = RequestQueue(lease_s=0.02)
    rid = q.submit(np.asarray([1, 2], np.int32), 4)
    # capture the claim generation as an INT at claim time — the
    # Request object is live and its claim_seq moves on re-claim
    # (the engine does the same via _Row.seq)
    old_seq = q.claim().claim_seq
    time.sleep(0.03)
    assert q.reap_expired() == [rid]
    # stale engine still holds the OLD claim generation
    assert q.fail(rid, RuntimeError("stale"), seq=old_seq) == "stale"
    fresh = q.claim()
    assert fresh.rid == rid and q.claim() is None   # exactly one copy
    assert q.complete(rid, [9, 9], seq=old_seq) is False
    assert q.request(rid).state == "running"        # not clobbered
    assert q.complete(rid, [5], seq=fresh.claim_seq) is True


def test_late_commit_never_resurrects_a_failed_request():
    q = RequestQueue(lease_s=0.02)
    rid = q.submit(np.asarray([1], np.int32), 2, max_retries=0)
    old_seq = q.claim().claim_seq
    time.sleep(0.03)
    q.reap_expired()
    q.claim()
    q.fail(rid, RuntimeError("terminal"))           # exhausts retries
    assert q.request(rid).state == "failed"
    assert q.complete(rid, [7], seq=old_seq) is False
    assert q.request(rid).state == "failed"         # stays terminal
    assert rid in q.failed and rid not in q.done


def test_engine_validates_geometry():
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    with pytest.raises(ValueError, match="max_seq"):
        Engine(params, mesh, CFG, ServeConfig(max_prompt=64,
                                              max_new=64))
    with pytest.raises(ValueError, match="pool holds"):
        Engine(params, mesh, CFG, ServeConfig(max_prompt=16,
                                              max_new=16, n_blocks=2))


# ---------------------------------------------------------------- r11:
# prefix caching + chunked prefill (ISSUE 8). The identity bar is
# UNCHANGED — whatever admission skipped (cache hits, partial hits,
# CoW-forked full hits) or streamed (chunked long prompts), every
# request's tokens are what greedy_generate produces for it alone.


def test_prefix_cache_hit_partial_and_full_identity():
    """Miss, full-block-aligned full hit (the CoW-recompute path) and
    partial hit all produce baseline-identical tokens, and the stats
    ledger records exactly what was skipped."""
    rng = np.random.default_rng(11)
    base_p = rng.integers(0, CFG.vocab, (12,)).astype(np.int32)
    part_p = np.concatenate([base_p[:8],
                             rng.integers(0, CFG.vocab, (3,))
                             .astype(np.int32)])
    eng = _engine(max_rows=1)          # serialize: A seeds the cache
    r_a = eng.submit(base_p, 8)
    eng.run()
    r_b = eng.submit(base_p, 8)        # full hit: 12 = 3 full blocks
    eng.run()
    r_c = eng.submit(part_p, 8)        # partial hit: blocks 0-1 only
    eng.run()
    for rid, p in [(r_a, base_p), (r_b, base_p), (r_c, part_p)]:
        req = eng.queue.request(rid)
        assert req.state == "done"
        np.testing.assert_array_equal(np.asarray(req.tokens),
                                      _baseline(CFG, p, 8))
    st = eng.prefix_stats()
    assert st["misses"] == 1 and st["hits"] == 2
    # full hit skips s-1 = 11 positions, partial hit skips 2 blocks = 8
    assert st["hit_tokens"] == 11 + 8
    assert st["full_hits"] == 1
    assert eng.queue.request(r_b).prefix_hit_tokens == 11
    assert eng.queue.request(r_c).prefix_hit_tokens == 8
    # blocks came back as reusable cache, not as live occupancy
    assert eng.pool.occupancy() == 0.0
    assert sum(a.n_cached for a in eng.pool.allocators) > 0


def test_prefix_cache_cow_fork_under_live_sharing():
    """Two same-prompt requests admitted together after the prefix is
    cached: both full-hit, and the one whose recompute write targets a
    block the other still maps must fork it copy-on-write — tokens
    stay baseline-identical and the fork fires at least once."""
    rng = np.random.default_rng(12)
    p = rng.integers(0, CFG.vocab, (8,)).astype(np.int32)
    eng = _engine(max_rows=2)
    r0 = eng.submit(p, 10)
    eng.run()                          # seed the cache
    rids = [eng.submit(p, 10) for _ in range(2)]
    eng.run()
    base = _baseline(CFG, p, 10)
    for rid in [r0, *rids]:
        np.testing.assert_array_equal(
            np.asarray(eng.queue.request(rid).tokens), base)
    st = eng.prefix_stats()
    assert st["full_hits"] == 2
    assert st["cow"] >= 1              # the live-sharing fork fired


def test_prefix_cache_off_recomputes_everything():
    rng = np.random.default_rng(13)
    p = rng.integers(0, CFG.vocab, (8,)).astype(np.int32)
    eng = _engine(prefix_cache=False)
    rids = [eng.submit(p, 8) for _ in range(2)]
    eng.run()
    base = _baseline(CFG, p, 8)
    for rid in rids:
        np.testing.assert_array_equal(
            np.asarray(eng.queue.request(rid).tokens), base)
    st = eng.prefix_stats()
    assert st["hits"] == 0 and st["misses"] == 0
    assert sum(a.n_cached for a in eng.pool.allocators) == 0


@pytest.mark.parametrize("dp,tp", [(2, 1), (2, 2)])
def test_prefix_cache_identity_across_meshes(dp, tp):
    """Shared-prefix traffic over dp/tp meshes: hits are per-shard
    (the index lives with each shard's allocator) and tokens match
    the solo baselines regardless of which shard served which copy."""
    rng = np.random.default_rng(14)
    p = rng.integers(0, CFG.vocab, (8,)).astype(np.int32)
    eng = _engine(dp=dp, tp=tp, max_rows=2 * dp)
    r0 = eng.submit(p, 8)
    eng.run()
    rids = [eng.submit(p, 8) for _ in range(2 * dp)]
    eng.run()
    base = _baseline(CFG, p, 8)
    for rid in [r0, *rids]:
        np.testing.assert_array_equal(
            np.asarray(eng.queue.request(rid).tokens), base)
    # every repeat that landed on the seeded shard (slot 0's) hit
    assert eng.prefix_stats()["hits"] >= 1


def test_chunked_prefill_streams_and_bounds_programs():
    """Prompts of every length through a small chunk: identity holds,
    and the compiled chunk-program count is bounded by the bucket
    ladder — NOT by the number of distinct prompt lengths (the r9
    per-length zoo this replaces)."""
    cfg = CFG
    lens = [3, 5, 8, 11, 14, 16, 19, 23, 26, 31]
    prompts = _workload(cfg, lens, seed=9)
    eng = _engine(max_rows=2, max_prompt=32, max_new=8, n_blocks=64,
                  prefill_chunk=8, prefix_cache=False)
    rids = [eng.submit(p, 6) for p in prompts]
    eng.run()
    for rid, p in zip(rids, prompts):
        req = eng.queue.request(rid)
        assert req.state == "done"
        np.testing.assert_array_equal(np.asarray(req.tokens),
                                      _baseline(cfg, p, 6))
    assert len(eng._chunk_fns) <= len(eng._chunk_widths)
    assert len(eng._chunk_widths) <= 5     # the "handful" bound
    # whole-prompt arm: chunk >= max_prompt -> every admission is one
    # chunk, still bucket-bounded
    eng2 = _engine(max_rows=2, max_prompt=32, max_new=8, n_blocks=64,
                   prefill_chunk=32, prefix_cache=False)
    rids2 = [eng2.submit(p, 6) for p in prompts[:4]]
    eng2.run()
    for rid, p in zip(rids2, prompts[:4]):
        np.testing.assert_array_equal(
            np.asarray(eng2.queue.request(rid).tokens),
            _baseline(cfg, p, 6))
    assert len(eng2._chunk_fns) <= len(eng2._chunk_widths)


def test_prefix_cache_eviction_under_pool_pressure():
    """A pool sized so that cached prefixes must be LRU-evicted to
    admit new traffic: admission never deadlocks on a cache-full pool
    and outputs stay identical."""
    prompts = _workload(CFG, [8, 8, 8, 8], seed=15)
    # 2 rows of ceil((8+8)/4)=4 blocks live + little slack
    eng = _engine(max_rows=2, max_prompt=8, max_new=8, n_blocks=9)
    rids = [eng.submit(p, 8) for p in prompts]
    eng.run()
    for rid, p in zip(rids, prompts):
        req = eng.queue.request(rid)
        assert req.state == "done"
        np.testing.assert_array_equal(np.asarray(req.tokens),
                                      _baseline(CFG, p, 8))
    assert eng.prefix_stats()["evictions"] > 0


@pytest.mark.parametrize("drafter", ["ngram", "suffix"])
def test_speculative_drafter_identity(drafter):
    """Both host drafters under k=3: proposals differ, tokens cannot
    — the verify window commits the full model's argmax regardless."""
    # a repetitive prompt gives both matchers something to chew on
    p = np.asarray([3, 7, 9, 3, 7, 9, 3, 7], np.int32)
    eng = _engine(speculate_k=3, drafter=drafter)
    rid = eng.submit(p, 12)
    eng.run()
    np.testing.assert_array_equal(
        np.asarray(eng.queue.request(rid).tokens),
        _baseline(CFG, p, 12))


def test_suffix_automaton_matches_and_proposes():
    from icikit.serve import SuffixAutomaton
    sam = SuffixAutomaton()
    for t in [1, 2, 3, 4, 1, 2, 3]:
        sam.feed(t)
    # suffix [1,2,3] occurred at positions 0-2 -> longest match 3,
    # continuation after that occurrence is 4 then 1, 2...
    assert sam.match_len == 3
    np.testing.assert_array_equal(sam.propose(3), [4, 1, 2])
    sam.feed(4)
    assert sam.match_len == 4
    np.testing.assert_array_equal(sam.propose(2), [1, 2])
    # no-match stream falls back to repeating the last token
    sam2 = SuffixAutomaton()
    for t in [5, 6, 7]:
        sam2.feed(t)
    assert sam2.match_len == 0
    np.testing.assert_array_equal(sam2.propose(2), [7, 7])


def test_speculative_overshoot_never_poisons_the_index():
    """A speculative window can accept past n_new (cursor overshoot);
    the finalize frontier must clamp to the RECORDED tokens, or a
    block holding real K/V would be registered under a zero-run chain
    hash and a later prompt could share wrong content. Pin: every
    index entry matches a chain hash reconstructible from some
    request's prompt + solo continuation."""
    from icikit.serve.kvpool import block_hashes

    bs = 2
    eng = _engine(max_rows=2, block_size=bs, n_blocks=48,
                  max_prompt=8, max_new=4, speculate_k=4,
                  drafter="ngram")
    rng = np.random.default_rng(17)
    prompts = [np.full((4,), 7, np.int32),
               np.asarray([3, 9, 3, 9], np.int32),
               rng.integers(0, CFG.vocab, (6,)).astype(np.int32)]
    rids = [eng.submit(p, 2) for p in prompts]
    rids += [eng.submit(p, 4) for p in prompts]
    eng.run()
    legal = set()
    for p in prompts:
        # the longest token run a block of this request could hold:
        # prompt + the FULL greedy continuation (overshot positions
        # hold continuation K/V, but their tokens were never
        # recorded, so no hash over them may exist)
        full = np.concatenate([p, _baseline(CFG, p, 8)])
        legal.update(block_hashes(full, bs))
    for a in eng.pool.allocators:
        with a._lock:
            index = dict(a._index)
        for h in index:
            assert h in legal, \
                "registered hash matches no request's token chain"
    for rid, p in zip(rids, prompts + prompts):
        req = eng.queue.request(rid)
        np.testing.assert_array_equal(
            np.asarray(req.tokens), _baseline(CFG, p, req.n_new)[
                :len(req.tokens)])


# ---------------------------------------------------------------- r12:
# sampled serving (schedule-invariant per-request keys) + in-flight
# prefill dedup. The sampled identity bar mirrors the greedy one:
# whatever the admission timing, co-batching, speculation, or mesh,
# a sampled request's tokens are bitwise what sample_generate draws
# for (prompt, seed, knobs) alone — base key jax.random.key(0),
# seeds=[request.seed], the canonical stream the engine stamps.


def _sample_baseline(cfg, prompt, n_new, seed, temperature=0.8,
                     top_k=0, top_p=0.9):
    from icikit.models.transformer.decode import sample_generate
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), cfg, mesh)
    out = sample_generate(params, jnp.asarray(prompt)[None], mesh, cfg,
                          n_new, jax.random.key(0),
                          temperature=temperature, top_k=top_k,
                          top_p=top_p, seeds=[seed])
    return np.asarray(out)[0, len(prompt):]


@pytest.mark.parametrize("speculate_k", [1, 3])
def test_sampled_identity_staggered_mixed_lengths(speculate_k):
    """Sampled requests over staggered admission × mixed prompt
    lengths × speculate on/off: every request's tokens are bitwise
    its solo sample_generate draw — the r12 acceptance bar."""
    prompts = _workload(CFG, [5, 8, 11, 8], seed=21)
    n_news = [6, 12, 9, 4]
    eng = _engine(speculate_k=speculate_k)
    t0 = time.monotonic()
    rids = [eng.submit(p, n, not_before=t0 + 0.01 * i, seed=50 + i,
                       temperature=0.8, top_p=0.9)
            for i, (p, n) in enumerate(zip(prompts, n_news))]
    assert eng.run() == len(rids)
    for i, (rid, p, n) in enumerate(zip(rids, prompts, n_news)):
        req = eng.queue.request(rid)
        assert req.state == "done"
        np.testing.assert_array_equal(
            np.asarray(req.tokens), _sample_baseline(CFG, p, n, 50 + i))


@pytest.mark.parametrize("dp,tp", [(2, 1), (2, 2)])
def test_sampled_identity_across_meshes(dp, tp):
    prompts = _workload(CFG, [6, 9, 6], seed=22)
    eng = _engine(dp=dp, tp=tp, max_rows=2 * dp)
    rids = [eng.submit(p, 8, seed=i, temperature=1.2, top_k=16)
            for i, p in enumerate(prompts)]
    eng.run()
    for i, (rid, p) in enumerate(zip(rids, prompts)):
        req = eng.queue.request(rid)
        assert req.state == "done"
        np.testing.assert_array_equal(
            np.asarray(req.tokens),
            _sample_baseline(CFG, p, 8, i, temperature=1.2, top_k=16,
                             top_p=1.0))


def test_mixed_greedy_sampled_cobatch_containment():
    """A greedy request co-batched with sampled neighbors is bitwise
    what the all-greedy engine serves (the sampled step variant maps
    temperature-0 rows to raw-logit argmax), and the sampled rows
    stay bitwise their solo draws."""
    prompts = _workload(CFG, [8, 8, 6], seed=23)
    eng = _engine(max_rows=3)
    r_g = eng.submit(prompts[0], 10)                       # greedy
    r_s1 = eng.submit(prompts[1], 10, seed=7, temperature=0.9)
    r_s2 = eng.submit(prompts[2], 8, seed=8, temperature=1.5,
                      top_p=0.8)
    eng.run()
    np.testing.assert_array_equal(
        np.asarray(eng.queue.request(r_g).tokens),
        _baseline(CFG, prompts[0], 10))
    np.testing.assert_array_equal(
        np.asarray(eng.queue.request(r_s1).tokens),
        _sample_baseline(CFG, prompts[1], 10, 7, temperature=0.9,
                         top_p=1.0))
    np.testing.assert_array_equal(
        np.asarray(eng.queue.request(r_s2).tokens),
        _sample_baseline(CFG, prompts[2], 8, 8, temperature=1.5,
                         top_p=0.8))


def test_sampled_seed_reissue_is_deterministic():
    """The same (prompt, seed, knobs) served twice — different
    admissions, different co-batches — commits identical tokens: the
    counter keys carry no engine state."""
    [p] = _workload(CFG, [8], seed=24)
    eng = _engine(max_rows=2)
    r1 = eng.submit(p, 10, seed=3, temperature=1.0)
    r2 = eng.submit(_workload(CFG, [5], seed=25)[0], 12)   # co-batch
    eng.run()
    r3 = eng.submit(p, 10, seed=3, temperature=1.0)        # alone
    eng.run()
    np.testing.assert_array_equal(
        np.asarray(eng.queue.request(r1).tokens),
        np.asarray(eng.queue.request(r3).tokens))
    assert eng.queue.request(r2).state == "done"


# -------------------------------------------------- in-flight dedup


def test_inflight_dedup_waiter_attaches_and_matches():
    """Two identical prompts admitted together: the second becomes a
    WAITER (no prefill compute for the shared blocks), both outputs
    are baseline-identical, and the compute ledger shows the dedup —
    prefiller pays s positions, waiter pays only the s-1 recompute."""
    rng = np.random.default_rng(31)
    p = rng.integers(0, CFG.vocab, (16,)).astype(np.int32)
    eng = _engine(max_rows=2, block_size=4, n_blocks=32, max_new=8,
                  prefill_chunk=4)
    rids = [eng.submit(p, 6) for _ in range(2)]
    eng.run()
    base = _baseline(CFG, p, 6)
    for rid in rids:
        np.testing.assert_array_equal(
            np.asarray(eng.queue.request(rid).tokens), base)
    st = eng.prefix_stats()
    assert st["inflight_hits"] == 1
    # 16 (prefiller) + 1 (waiter's s-1 recompute), not 32
    assert st["prefill_tokens"] == 17
    assert st["inflight_hit_tokens"] == 15


def test_inflight_dedup_off_recomputes_concurrently():
    rng = np.random.default_rng(32)
    p = rng.integers(0, CFG.vocab, (16,)).astype(np.int32)
    eng = _engine(max_rows=2, block_size=4, n_blocks=32, max_new=8,
                  prefill_chunk=4, inflight_dedup=False)
    rids = [eng.submit(p, 6) for _ in range(2)]
    eng.run()
    base = _baseline(CFG, p, 6)
    for rid in rids:
        np.testing.assert_array_equal(
            np.asarray(eng.queue.request(rid).tokens), base)
    st = eng.prefix_stats()
    assert st["inflight_hits"] == 0
    assert st["prefill_tokens"] == 32          # both computed fully


def test_inflight_dedup_without_prefix_cache_rejected():
    """Explicitly arming dedup with the cache off is a loud config
    error (the silent no-op would read as "dedup delivers nothing" in
    an A/B); the "auto" default just follows prefix_cache."""
    with pytest.raises(ValueError, match="requires prefix_cache"):
        _engine(prefix_cache=False, inflight_dedup=True)
    with pytest.raises(ValueError, match="unknown inflight_dedup"):
        _engine(inflight_dedup="on")
    eng = _engine(prefix_cache=False)          # auto -> off, no raise
    assert not eng.dedup
    assert _engine().dedup


def test_inflight_dedup_prefix_extension_waiter():
    """A waiter whose prompt EXTENDS the in-flight prefix: waits for
    the shared blocks, then computes only its own suffix."""
    rng = np.random.default_rng(33)
    shared = rng.integers(0, CFG.vocab, (12,)).astype(np.int32)
    ext = np.concatenate([shared,
                          rng.integers(0, CFG.vocab, (4,))
                          .astype(np.int32)])
    eng = _engine(max_rows=2, block_size=4, n_blocks=32,
                  max_prompt=16, max_new=8, prefill_chunk=4)
    r_a = eng.submit(shared, 6)
    r_b = eng.submit(ext, 6)
    eng.run()
    np.testing.assert_array_equal(
        np.asarray(eng.queue.request(r_a).tokens),
        _baseline(CFG, shared, 6))
    np.testing.assert_array_equal(
        np.asarray(eng.queue.request(r_b).tokens),
        _baseline(CFG, ext, 6))
    st = eng.prefix_stats()
    assert st["inflight_hits"] == 1
    # A pays 12; B pays its 4-token suffix only
    assert st["prefill_tokens"] == 12 + 4


def test_inflight_waiter_falls_back_when_prefiller_vanishes():
    """White-box: evict the prefiller mid-prefill (the preemption
    path withdraws its announcements) — the waiter stops waiting,
    computes the blocks itself, and both requests complete with
    baseline tokens through the normal requeue."""
    rng = np.random.default_rng(34)
    p = rng.integers(0, CFG.vocab, (16,)).astype(np.int32)
    eng = _engine(max_rows=2, block_size=4, n_blocks=32, max_new=8,
                  prefill_chunk=4)
    r_a = eng.submit(p, 6)
    r_b = eng.submit(p, 6)
    eng._admit()
    row_b = eng.rows[1]
    assert row_b is not None and row_b.waiting
    eng._advance_prefill()                     # A computes one chunk
    assert eng.rows[1].waiting                 # B still waiting
    row_a = eng.rows[0]
    eng._evict(0)                              # preempt the prefiller
    eng.queue.release(row_a.req.rid, seq=row_a.seq)
    eng.run()
    base = _baseline(CFG, p, 6)
    for rid in (r_a, r_b):
        req = eng.queue.request(rid)
        assert req.state == "done"
        np.testing.assert_array_equal(np.asarray(req.tokens), base)


def test_inflight_dedup_sampled_duplicates_share_stream():
    """Duplicate sampled prompts with the SAME seed: dedup shares the
    prefill AND both commit the identical sampled continuation; a
    different seed diverges after the shared prefix."""
    rng = np.random.default_rng(35)
    p = rng.integers(0, CFG.vocab, (16,)).astype(np.int32)
    eng = _engine(max_rows=3, block_size=4, n_blocks=48, max_new=8,
                  prefill_chunk=4)
    r1 = eng.submit(p, 6, seed=1, temperature=0.9)
    r2 = eng.submit(p, 6, seed=1, temperature=0.9)
    r3 = eng.submit(p, 6, seed=2, temperature=0.9)
    eng.run()
    want1 = _sample_baseline(CFG, p, 6, 1, temperature=0.9,
                             top_p=1.0)
    want2 = _sample_baseline(CFG, p, 6, 2, temperature=0.9,
                             top_p=1.0)
    np.testing.assert_array_equal(
        np.asarray(eng.queue.request(r1).tokens), want1)
    np.testing.assert_array_equal(
        np.asarray(eng.queue.request(r2).tokens), want1)
    np.testing.assert_array_equal(
        np.asarray(eng.queue.request(r3).tokens), want2)
    assert eng.prefix_stats()["inflight_hits"] == 2


def test_finalize_frontier_clamps_to_recorded_tokens():
    """White-box pin of the overshoot clamp: a cursor past
    s_prompt + n_done (speculative windows accept beyond n_new) must
    not finalize — and in particular not content-register — blocks
    whose tokens were never recorded."""
    from icikit.serve.engine import _Row

    eng = _engine(max_rows=1, block_size=2, n_blocks=32, max_prompt=8,
                  max_new=8)
    rid = eng.submit(np.asarray([1, 2, 3, 4], np.int32), 2)
    eng.run()
    req = eng.queue.request(rid)
    owner = "wb.overshoot"
    eng.pool.ensure(owner, 0, 8)
    row = _Row(req=req, shard=0, s_prompt=4, n_done=2, sealed=0,
               prefilled=4, owner=owner)
    eng.rows[0] = row
    eng._seq_buf[0] = 0
    eng._seq_buf[0, :6] = [1, 2, 3, 4, 9, 8]   # prompt + 2 recorded
    eng._curs[0] = 8                           # overshot cursor
    eng._finalize_blocks(0, row)
    # recorded frontier = 6 -> blocks (0,1),(2,3),(4,5) finalize,
    # the block holding unrecorded positions (6,7) must NOT
    assert row.sealed == 3
    from icikit.serve.kvpool import block_hashes
    a = eng.pool.allocators[0]
    chains = block_hashes(eng._seq_buf[0, :8], 2)
    # every recorded chain is indexed (here or on an earlier page —
    # first registration wins); the zero-run chain past the recorded
    # frontier must not exist
    assert all(a.indexed(h) is not None for h in chains[:3])
    assert a.indexed(chains[3]) is None
    eng.rows[0] = None
    eng.pool.release(owner, 0)
