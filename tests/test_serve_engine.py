"""Serving engine: token identity vs single-request generate, plus
admission / eviction / preemption mechanics.

The load-bearing property is the acceptance bar from ROADMAP item 1:
whatever the admission timing, co-batching, prompt-length mix,
speculative mode, or mesh, every request's output tokens are
bitwise what ``greedy_generate`` produces for that request alone.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from icikit.models.transformer import (
    TransformerConfig,
    greedy_generate,
    init_params,
)
from icikit.models.transformer.model import make_model_mesh
from icikit.serve import Engine, RequestQueue, ServeConfig

CFG = TransformerConfig(vocab=61, d_model=32, n_heads=4, d_head=8,
                        d_ff=64, n_layers=2, max_seq=64,
                        compute_dtype="float32")


def _baseline(cfg, prompt, n_new):
    """Single-request greedy reference on a dp=1/tp=1 mesh (tokens are
    mesh-independent — pinned by tests/test_decode.py)."""
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), cfg, mesh)
    out = greedy_generate(params, jnp.asarray(prompt)[None], mesh, cfg,
                          n_new)
    return np.asarray(out)[0, len(prompt):]


def _workload(cfg, lens, seed=1):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, cfg.vocab, (s,)).astype(np.int32)
            for s in lens]


def _engine(cfg=CFG, dp=1, tp=1, **over):
    mesh = make_model_mesh(dp=dp, tp=tp, sp=1)
    params = init_params(jax.random.key(0), cfg, mesh)
    sv = dict(max_rows=2, block_size=4, n_blocks=32, max_prompt=16,
              max_new=16)
    sv.update(over)
    return Engine(params, mesh, cfg, ServeConfig(**sv))


@pytest.mark.parametrize("speculate_k", [1, 3])
def test_mixed_lengths_staggered_admission_identity(speculate_k):
    """4 requests over 2 rows, three prompt lengths, staggered
    arrivals: every request's tokens match its solo baseline."""
    prompts = _workload(CFG, [5, 8, 11, 8])
    n_news = [6, 12, 9, 4]
    eng = _engine(speculate_k=speculate_k)
    t0 = time.monotonic()
    rids = [eng.submit(p, n, not_before=t0 + 0.01 * i)
            for i, (p, n) in enumerate(zip(prompts, n_news))]
    assert eng.run() == len(rids)
    for rid, p, n in zip(rids, prompts, n_news):
        req = eng.queue.request(rid)
        assert req.state == "done"
        np.testing.assert_array_equal(np.asarray(req.tokens),
                                      _baseline(CFG, p, n))


@pytest.mark.parametrize("dp,tp", [(2, 1), (1, 2), (2, 2)])
def test_identity_across_meshes(dp, tp):
    prompts = _workload(CFG, [6, 9, 6])
    eng = _engine(dp=dp, tp=tp, max_rows=2 * dp)
    rids = [eng.submit(p, 8) for p in prompts]
    eng.run()
    for rid, p in zip(rids, prompts):
        req = eng.queue.request(rid)
        assert req.state == "done"
        np.testing.assert_array_equal(np.asarray(req.tokens),
                                      _baseline(CFG, p, 8))


def test_eos_freezes_and_frees_the_row():
    """A request with eos_id stops at the first EOS token (inclusive)
    — the engine's output is the solo continuation truncated at EOS,
    and the freed row admits the next request."""
    [prompt] = _workload(CFG, [8], seed=3)
    base = _baseline(CFG, prompt, 12)
    eos = int(base[4])       # force an early stop at a real token
    upto = list(base).index(eos) + 1
    eng = _engine(max_rows=2)
    r1 = eng.submit(prompt, 12, eos_id=eos)
    r2 = eng.submit(prompt, 12)      # no EOS: runs to n_new
    eng.run()
    req1, req2 = eng.queue.request(r1), eng.queue.request(r2)
    np.testing.assert_array_equal(np.asarray(req1.tokens), base[:upto])
    np.testing.assert_array_equal(np.asarray(req2.tokens), base)
    assert req1.done_t <= req2.done_t


def test_single_token_request_finishes_at_prefill():
    [prompt] = _workload(CFG, [7], seed=4)
    eng = _engine()
    rid = eng.submit(prompt, 1)
    eng.run()
    req = eng.queue.request(rid)
    assert req.state == "done"
    np.testing.assert_array_equal(np.asarray(req.tokens),
                                  _baseline(CFG, prompt, 1))
    assert eng.pool.occupancy() == 0.0   # blocks returned


def test_pool_preemption_retries_to_completion():
    """A pool too small for two rows admits serially: the second
    request is preempted at admission (no retry burned), backs off,
    and completes with identical tokens once the first evicts."""
    prompts = _workload(CFG, [8, 8], seed=5)
    # one row's worst case needs ceil((8+12)/4)=5 blocks; give 7 so
    # both admit but cannot both extend to full length
    eng = _engine(n_blocks=7, max_prompt=8, max_new=12)
    rids = [eng.submit(p, 12, max_retries=0) for p in prompts]
    eng.run()
    pre = 0
    for rid, p in zip(rids, prompts):
        req = eng.queue.request(rid)
        assert req.state == "done"     # max_retries=0: preemption must
        pre += req.preempted           # not have consumed a retry
        np.testing.assert_array_equal(np.asarray(req.tokens),
                                      _baseline(CFG, p, 12))
    assert pre >= 1
    assert eng.pool.occupancy() == 0.0


def test_occupancy_and_slo_marks():
    prompts = _workload(CFG, [8, 8, 8, 8], seed=6)
    eng = _engine(max_rows=2)
    rids = [eng.submit(p, 8) for p in prompts]
    eng.run()
    assert 0.5 < eng.occupancy_mean() <= 1.0
    for rid in rids:
        slo = eng.queue.request(rid).slo()
        assert slo["ttft_ms"] >= slo["queue_wait_ms"] >= 0.0
        assert slo["tpot_ms"] > 0.0
        assert slo["n_tokens"] == 8


def test_queue_lease_expiry_reissues():
    """Scheduler-level dead-engine story: a claimed request whose
    lease is never renewed comes back on reap."""
    q = RequestQueue(lease_s=0.03)
    rid = q.submit(np.asarray([1, 2], np.int32), 4)
    req = q.claim()
    assert req.rid == rid and q.claim() is None
    assert q.reap_expired() == []          # lease still fresh
    time.sleep(0.04)
    assert q.reap_expired() == [rid]
    again = q.claim()
    assert again.rid == rid and again.attempts == 2


def test_queue_complete_is_idempotent():
    q = RequestQueue()
    rid = q.submit(np.asarray([1], np.int32), 2)
    q.claim()
    assert q.complete(rid, [5, 6]) is True
    assert q.complete(rid, [7, 8]) is False     # late duplicate
    assert q.request(rid).tokens == [5, 6]      # first commit won
    assert q.n_duplicate_commits == 1
    assert q.drained()


def test_queue_retry_backoff_then_fail():
    q = RequestQueue(backoff_s=0.01)
    rid = q.submit(np.asarray([1], np.int32), 2, max_retries=1)
    q.claim()
    assert q.fail(rid, RuntimeError("boom")) == "queued"
    assert q.claim() is None               # backoff gates visibility
    time.sleep(0.015)
    assert q.claim().rid == rid
    assert q.fail(rid, RuntimeError("boom2")) == "failed"
    assert rid in q.failed and "boom2" in q.failed[rid].error
    assert q.drained()


def test_stale_engine_cannot_double_queue_or_mutate():
    """A reaped lease fences the old claimant: its fail() is a stale
    no-op (no duplicate heap entry -> no double admission) and its
    late complete() cannot commit over the reissued attempt."""
    q = RequestQueue(lease_s=0.02)
    rid = q.submit(np.asarray([1, 2], np.int32), 4)
    # capture the claim generation as an INT at claim time — the
    # Request object is live and its claim_seq moves on re-claim
    # (the engine does the same via _Row.seq)
    old_seq = q.claim().claim_seq
    time.sleep(0.03)
    assert q.reap_expired() == [rid]
    # stale engine still holds the OLD claim generation
    assert q.fail(rid, RuntimeError("stale"), seq=old_seq) == "stale"
    fresh = q.claim()
    assert fresh.rid == rid and q.claim() is None   # exactly one copy
    assert q.complete(rid, [9, 9], seq=old_seq) is False
    assert q.request(rid).state == "running"        # not clobbered
    assert q.complete(rid, [5], seq=fresh.claim_seq) is True


def test_late_commit_never_resurrects_a_failed_request():
    q = RequestQueue(lease_s=0.02)
    rid = q.submit(np.asarray([1], np.int32), 2, max_retries=0)
    old_seq = q.claim().claim_seq
    time.sleep(0.03)
    q.reap_expired()
    q.claim()
    q.fail(rid, RuntimeError("terminal"))           # exhausts retries
    assert q.request(rid).state == "failed"
    assert q.complete(rid, [7], seq=old_seq) is False
    assert q.request(rid).state == "failed"         # stays terminal
    assert rid in q.failed and rid not in q.done


def test_engine_validates_geometry():
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    with pytest.raises(ValueError, match="max_seq"):
        Engine(params, mesh, CFG, ServeConfig(max_prompt=64,
                                              max_new=64))
    with pytest.raises(ValueError, match="pool holds"):
        Engine(params, mesh, CFG, ServeConfig(max_prompt=16,
                                              max_new=16, n_blocks=2))
