"""Sequence-parallel attention vs the dense oracle (both schedules must
reproduce single-device attention exactly, like the collective pattern
oracles reproduce the closed-form payloads)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from icikit.models.attention import (
    dense_attention,
    ring_attention,
    ulysses_attention,
)
from icikit.utils.mesh import make_mesh, shard_along


def _qkv(b=2, s=32, h=4, d=8, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(
        rng.standard_normal((b, s, h, d)).astype(dtype))
    return mk(), mk(), mk()


def _shard(mesh, *arrs):
    return tuple(shard_along(a, mesh, dim=1) for a in arrs)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_dense(mesh8, causal):
    q, k, v = _qkv()
    expected = np.asarray(dense_attention(q, k, v, causal=causal))
    qs, ks, vs = _shard(mesh8, q, k, v)
    out = np.asarray(ring_attention(qs, ks, vs, mesh8, causal=causal))
    np.testing.assert_allclose(out, expected, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("algorithm", ["xla", "hypercube", "wraparound"])
def test_ulysses_matches_dense(mesh8, causal, algorithm):
    q, k, v = _qkv(h=8, seed=1)
    expected = np.asarray(dense_attention(q, k, v, causal=causal))
    qs, ks, vs = _shard(mesh8, q, k, v)
    out = np.asarray(ulysses_attention(
        qs, ks, vs, mesh8, causal=causal, algorithm=algorithm))
    np.testing.assert_allclose(out, expected, rtol=2e-5, atol=2e-5)


def test_ring_non_pow2_mesh():
    """The ring schedule works for any device count (like the
    reference's ring, ``Communication/src/main.cc:190-223``)."""
    mesh = make_mesh(6)
    q, k, v = _qkv(s=30, seed=2)
    expected = np.asarray(dense_attention(q, k, v, causal=True))
    qs, ks, vs = _shard(mesh, q, k, v)
    out = np.asarray(ring_attention(qs, ks, vs, mesh, causal=True))
    np.testing.assert_allclose(out, expected, rtol=2e-5, atol=2e-5)


def test_ring_gradients_match_dense(mesh8):
    """Ring attention is differentiable end-to-end — the property the
    training step depends on."""
    q, k, v = _qkv(s=16, seed=3)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh8, causal=True) ** 2)

    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    qs, ks, vs = _shard(mesh8, q, k, v)
    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(qs, ks, vs)
    for gd, gr in zip(g_dense, g_ring):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                   rtol=5e-4, atol=5e-5)


def test_ring_bf16_io_f32_accumulate(mesh8):
    """bf16 inputs stay bf16 at the boundary; accumulation runs in f32
    (MXU-friendly convention)."""
    q, k, v = _qkv(seed=4, dtype=np.float32)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    qs, ks, vs = _shard(mesh8, qb, kb, vb)
    out = ring_attention(qs, ks, vs, mesh8, causal=True)
    assert out.dtype == jnp.bfloat16
    expected = dense_attention(qb, kb, vb, causal=True)
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32),
        np.asarray(expected, dtype=np.float32), rtol=0.1, atol=0.1)


def test_shape_validation(mesh8):
    q, k, v = _qkv(s=30)  # 30 not divisible by 8
    with pytest.raises(ValueError, match="sequence length"):
        ring_attention(q, k, v, mesh8)
    q, k, v = _qkv(s=32, h=6)  # 6 heads not divisible by 8
    with pytest.raises(ValueError, match="head count"):
        ulysses_attention(q, k, v, mesh8)


def test_p1_degenerate(mesh1):
    q, k, v = _qkv(seed=5)
    expected = np.asarray(dense_attention(q, k, v, causal=True))
    out_r = np.asarray(ring_attention(q, k, v, mesh1, causal=True))
    out_u = np.asarray(ulysses_attention(q, k, v, mesh1, causal=True,
                                         algorithm="hypercube"))
    np.testing.assert_allclose(out_r, expected, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(out_u, expected, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("schedule", ["ring", "zigzag"])
def test_gqa_kv_heads_rotate_unrepeated(mesh8, schedule):
    """GQA: ring/zigzag accept h_kv < h and match the dense oracle on
    repeated K/V — the rotating messages stay at K/V width."""
    from icikit.models.attention import zigzag_attention
    b, s, h, hkv, d = 2, 32, 8, 2, 8
    rng = np.random.default_rng(7)
    q = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, s, hkv, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, s, hkv, d)).astype(np.float32))
    kr = jnp.repeat(k, h // hkv, axis=2)
    vr = jnp.repeat(v, h // hkv, axis=2)
    expected = np.asarray(dense_attention(q, kr, vr, causal=True))
    fn = ring_attention if schedule == "ring" else zigzag_attention
    qs, ks, vs = (shard_along(a, mesh8, dim=1) for a in (q, k, v))
    out = np.asarray(fn(qs, ks, vs, mesh8, causal=True))
    np.testing.assert_allclose(out, expected, rtol=2e-5, atol=2e-5)


def test_gqa_head_divisibility_validated(mesh8):
    q, k, v = _qkv(h=4)
    with pytest.raises(ValueError, match="multiple of K/V heads"):
        ring_attention(q, k[:, :, :3], v[:, :, :3], mesh8)


@pytest.mark.parametrize("algorithm", ["xla", "hypercube", "wraparound"])
@pytest.mark.parametrize("hkv", [2, 4])
def test_gqa_ulysses_matches_dense(hkv, algorithm):
    """GQA through ulysses on a p=2 mesh: both head counts divide p,
    so K/V re-shard at their own width (a2a volume / n_rep) and the
    result matches the repeated-KV dense oracle — under every carrier
    schedule (the non-xla block reshape is a distinct code path)."""
    from icikit.models.attention import ulysses_attention
    mesh = make_mesh(2)
    b, s, h, d = 2, 16, 8, 8
    rng = np.random.default_rng(8)
    q = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, s, hkv, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, s, hkv, d)).astype(np.float32))
    rep = h // hkv
    expected = np.asarray(dense_attention(
        q, jnp.repeat(k, rep, 2), jnp.repeat(v, rep, 2), causal=True))
    qs, ks, vs = (shard_along(a, mesh, dim=1) for a in (q, k, v))
    out = np.asarray(ulysses_attention(qs, ks, vs, mesh, causal=True,
                                       algorithm=algorithm))
    np.testing.assert_allclose(out, expected, rtol=2e-5, atol=2e-5)


def test_gqa_ulysses_group_split(mesh8):
    """h_kv=2 does not divide p=8 but p % h_kv == 0: kv-head groups
    split with per-device replication (each kv head replicated p/h_kv
    times pre-wire — width p, not the full-repeat fallback's h) and
    the result matches the oracle."""
    from icikit.models.attention import ulysses_attention
    b, s, h, hkv, d = 2, 32, 8, 2, 8
    rng = np.random.default_rng(9)
    q = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, s, hkv, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, s, hkv, d)).astype(np.float32))
    expected = np.asarray(dense_attention(
        q, jnp.repeat(k, 4, 2), jnp.repeat(v, 4, 2), causal=True))
    qs, ks, vs = (shard_along(a, mesh8, dim=1) for a in (q, k, v))
    out = np.asarray(ulysses_attention(qs, ks, vs, mesh8, causal=True))
    np.testing.assert_allclose(out, expected, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("algorithm", ["xla", "wraparound"])
def test_gqa_ulysses_group_split_multihead(algorithm):
    """Group split with h/p > 1 local query heads per resident kv head
    (p=4, h=16, h_kv=2: f=2 replicas pre-wire, 4 q heads served
    locally), under both carrier kinds."""
    from icikit.models.attention import ulysses_attention
    mesh = make_mesh(4)
    b, s, h, hkv, d = 2, 32, 16, 2, 8
    rng = np.random.default_rng(10)
    q = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, s, hkv, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, s, hkv, d)).astype(np.float32))
    expected = np.asarray(dense_attention(
        q, jnp.repeat(k, 8, 2), jnp.repeat(v, 8, 2), causal=True))
    qs, ks, vs = (shard_along(a, mesh, dim=1) for a in (q, k, v))
    out = np.asarray(ulysses_attention(qs, ks, vs, mesh, causal=True,
                                       algorithm=algorithm))
    np.testing.assert_allclose(out, expected, rtol=2e-5, atol=2e-5)


def test_gqa_ulysses_irreducible_fallback(mesh8):
    """p=8 and h_kv=6 share no useful factor (neither divides the
    other): the full-width pre-repeat fallback still matches."""
    from icikit.models.attention import ulysses_attention
    b, s, h, hkv, d = 1, 32, 24, 6, 8
    rng = np.random.default_rng(11)
    q = jnp.asarray(rng.standard_normal((b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, s, hkv, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, s, hkv, d)).astype(np.float32))
    expected = np.asarray(dense_attention(
        q, jnp.repeat(k, 4, 2), jnp.repeat(v, 4, 2), causal=True))
    qs, ks, vs = (shard_along(a, mesh8, dim=1) for a in (q, k, v))
    out = np.asarray(ulysses_attention(qs, ks, vs, mesh8, causal=True))
    np.testing.assert_allclose(out, expected, rtol=2e-5, atol=2e-5)
