"""Decode-throughput bench harness smoke (tiny preset, CPU mesh)."""

from icikit.bench.decode import decode_bytes_per_token, run_bench


def test_decode_bench_tiny():
    rec = run_bench("tiny", dp=1, tp=1, batch=2, prompt_len=8, n_new=4,
                    runs=1)
    assert rec["unit"] == "tokens/s" and rec["value"] > 0
    assert rec["per_token_ms"] > 0
    assert rec["metric"].startswith("decode_tiny_")


def test_decode_bench_sampling_and_gqa():
    rec = run_bench("tiny", dp=1, tp=1, batch=2, prompt_len=8, n_new=4,
                    sampling="sample", runs=1, kv_heads=2)
    assert rec["value"] > 0


def test_decode_bytes_accounting():
    from icikit.bench.train import PRESETS
    from icikit.models.transformer import TransformerConfig
    cfg = TransformerConfig(**PRESETS["tiny"])
    b1 = decode_bytes_per_token(cfg, batch=1, cache_len=16)
    b2 = decode_bytes_per_token(cfg, batch=1, cache_len=32)
    assert b2 > b1  # longer cache reads more


def test_decode_bench_speculative():
    rec = run_bench("tiny", dp=1, tp=1, batch=2, prompt_len=8, n_new=8,
                    runs=1, speculate=3, draft_layers=1)
    assert rec["speculate"] == 3 and rec["draft_layers"] == 1
    assert 0.0 <= rec["acceptance_rate"] <= 1.0
    assert 1.0 <= rec["tokens_per_step"] <= 3.0
    # the acceptance × cost model rides on every speculative row
    assert rec["projected_eff_ms_per_token"] > 0
    assert "_spec3d1" in rec["metric"]
    assert rec["backend"]  # provenance: rows from CPU and TPU differ


def test_decode_bench_trained_drafter():
    """--drafter trained builds the draft branch (random-init) and the
    row carries the drafter tag — the wall-time machinery path."""
    rec = run_bench("tiny", dp=1, tp=1, batch=2, prompt_len=8, n_new=8,
                    runs=1, speculate=2, draft_layers=1,
                    drafter="trained")
    assert rec["drafter"] == "trained"
    assert "_spec2d1_trained" in rec["metric"]
    assert 0.0 <= rec["acceptance_rate"] <= 1.0


def test_cost_model_from_records(tmp_path):
    """The reproducible-verdict path: measured acceptance rows in,
    priced projection rows out — last row per (k, L_d, drafter) wins,
    depth fractions map onto the pricing preset."""
    import json
    from icikit.bench.decode import cost_model_rows
    path = tmp_path / "acc.jsonl"
    rows = [
        # superseded older measurement (lower α) — must NOT be priced
        {"kind": "acceptance", "batch": 1, "k": 2, "draft_layers": 1,
         "n_layers": 4, "drafter": "trained", "acceptance_rate": 0.10,
         "train_steps": 100},
        {"kind": "acceptance", "batch": 1, "k": 2, "draft_layers": 1,
         "n_layers": 4, "drafter": "trained", "acceptance_rate": 0.40,
         "train_steps": 3000},
        # r7-style row without a drafter field -> "shared"
        {"kind": "acceptance", "batch": 1, "k": 2, "draft_layers": 2,
         "n_layers": 4, "acceptance_rate": 0.15, "train_steps": 3000},
        # other batch: excluded at alpha_batch=1
        {"kind": "acceptance", "batch": 8, "k": 2, "draft_layers": 1,
         "n_layers": 4, "drafter": "trained", "acceptance_rate": 0.9},
    ]
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    out = cost_model_rows(str(path), preset="base", alpha_batch=1)
    assert len(out) == 2
    by = {(r["k"], r["draft_fraction"], r["drafter"]): r for r in out}
    tr = by[(2, 0.25, "trained")]
    assert tr["measured_acceptance"] == 0.40          # latest row won
    assert tr["draft_layers"] == 3                    # 12 * 0.25
    assert tr["alpha_train_steps"] == 3000
    # α=0.40 beats the ~0.336 quarter-depth break-even
    assert tr["measured_acceptance"] > tr["breakeven_acceptance"]
    assert tr["projected_eff_ms_per_token"] < tr["model_floor_ms"]
    sh = by[(2, 0.5, "shared")]
    assert sh["draft_layers"] == 6
    assert sh["measured_acceptance"] < sh["breakeven_acceptance"]


def test_cost_model_requires_acceptance_rows(tmp_path):
    import pytest
    from icikit.bench.decode import cost_model_rows
    path = tmp_path / "empty.jsonl"
    path.write_text("")
    with pytest.raises(ValueError, match="acceptance"):
        cost_model_rows(str(path))


def test_spec_breakeven_batch_rows():
    """Batch-aware pricing (ROADMAP 3c): rows exist per b, the b=1 row
    anchors on the committed measured floor, and the model's verdict
    shape holds — break-even α drifts DOWN with b (the truncated
    drafter re-reads only its depth fraction of the b-scaled cache)
    while the absolute baseline worsens."""
    from icikit.bench.decode import SPEC_FLOOR_MS, spec_breakeven_rows
    rows = spec_breakeven_rows(preset="base", batches=(1, 4, 16))
    assert len(rows) == 3 * 3 * 2     # b x k x frac
    by = {(r["batch"], r["k"], r["draft_fraction"]): r for r in rows}
    b1 = by[(1, 2, 0.25)]
    assert b1["baseline_source"] == "measured-floor"
    assert b1["baseline_ms_per_token"] == SPEC_FLOOR_MS
    # the b=1 break-even must agree with the r8 committed ~0.336
    assert abs(b1["breakeven_acceptance"] - 0.336) < 0.01
    for k in (2, 4, 8):
        for frac in (0.25, 0.5):
            be = [by[(b, k, frac)]["breakeven_acceptance"]
                  for b in (1, 4, 16)]
            assert be[0] >= be[1] >= be[2]          # drifts down
    base = [by[(b, 2, 0.25)]["baseline_ms_per_token"]
            for b in (1, 4, 16)]
    assert base[0] < base[1] < base[2]              # cache term grows
    for r in rows:
        assert r["kind"] == "breakeven"
        assert 0 < r["breakeven_acceptance"] \
            < r["breakeven_acceptance_15pct"]
        if r["batch"] > 1:
            assert r["baseline_source"] == "modeled"


def test_spec_cost_model_anchors():
    """At tokens_per_step = 1 and k = 1 the model must reproduce the
    baseline floor exactly (no drafts, one verify pass = one
    single-token step); more tokens per step must strictly help."""
    from icikit.bench.decode import spec_cost_model
    from icikit.bench.train import PRESETS
    from icikit.models.transformer import TransformerConfig
    cfg = TransformerConfig(**PRESETS["base"])
    m1 = spec_cost_model(cfg, 1, 320, k=1, draft_layers=6,
                         tokens_per_step=1.0)
    assert m1["projected_eff_ms_per_token"] == m1["model_floor_ms"]
    m2 = spec_cost_model(cfg, 1, 320, k=4, draft_layers=6,
                         tokens_per_step=3.0)
    m3 = spec_cost_model(cfg, 1, 320, k=4, draft_layers=6,
                         tokens_per_step=1.5)
    assert m2["projected_eff_ms_per_token"] < m3[
        "projected_eff_ms_per_token"]


# -- token-tree pricing (round 14) -----------------------------------

def test_tree_bytes_b1_is_chain():
    """tree_branch=1 degenerates the tree byte model to the chain's,
    exactly — the pricing analog of the b=1 bitwise program pin."""
    from icikit.bench.decode import (
        spec_bytes_per_iter,
        tree_bytes_per_iter,
    )
    from icikit.bench.train import PRESETS
    from icikit.models.transformer import TransformerConfig
    cfg = TransformerConfig(**PRESETS["base"])
    chain = spec_bytes_per_iter(cfg, 1, 320, 4, 3)
    tree = tree_bytes_per_iter(cfg, 1, 320, 4, 3, tree_branch=1)
    assert tree == chain
    # and bytes/pass grow with TREE SIZE at fixed depth
    b2 = sum(tree_bytes_per_iter(cfg, 1, 320, 4, 3, tree_branch=2))
    b4 = sum(tree_bytes_per_iter(cfg, 1, 320, 4, 3, tree_branch=4))
    assert sum(chain) < b2 < b4
    # zero-cost drafter: no draft bytes at any branch count
    d0, _ = tree_bytes_per_iter(cfg, 1, 320, 4, 3, tree_branch=2,
                                drafter_free=True)
    assert d0 == 0.0


def test_tree_expected_accept_estimator():
    from icikit.bench.decode import (
        tree_accept_params,
        tree_expected_accept,
    )
    # p_side = 0 is the chain expectation 1 + (k-1)alpha-ish
    # (truncated geometric): exact at the extremes
    assert tree_expected_accept(0.0, 0.0, 4) == 1.0
    assert tree_expected_accept(1.0, 0.0, 4) == 4.0
    # sideways help is monotone, bounded by one extra commit
    e0 = tree_expected_accept(0.4, 0.0, 4)
    e5 = tree_expected_accept(0.4, 0.5, 4)
    e1 = tree_expected_accept(0.4, 1.0, 4)
    assert e0 < e5 < e1 <= e0 + 1.0
    # round-trip: a synthetic measured row at known (alpha, p_side)
    # recovers both parameters
    alpha, p_side, k, steps = 0.35, 0.6, 4, 100_000
    d = k - 1
    em = alpha * (1 - alpha ** d) / (1 - alpha)
    row = {"k": k, "row_steps": steps,
           "primary_accepted": em * steps,
           "sideways_accepted": p_side * (1 - alpha ** d) * steps}
    a_hat, p_hat = tree_accept_params(row)
    assert abs(a_hat - alpha) < 1e-6
    assert abs(p_hat - p_side) < 1e-6


def test_cost_model_understands_tree_records(tmp_path):
    """--alpha-from with tree acceptance rows: keyed per branch
    count, measured tokens_per_step priced directly (it carries the
    sideways commits), estimator fit carried beside it."""
    import json
    from icikit.bench.decode import cost_model_rows
    path = tmp_path / "acc.jsonl"
    rows = [
        {"kind": "acceptance", "batch": 1, "k": 3, "draft_layers": 1,
         "n_layers": 4, "drafter": "ngram", "acceptance_rate": 0.30},
        {"kind": "acceptance", "batch": 1, "k": 3, "draft_layers": 1,
         "n_layers": 4, "drafter": "ngram", "acceptance_rate": 0.38,
         "tree_branch": 4, "tokens_per_step": 1.95, "row_steps": 200,
         "primary_accepted": 120, "sideways_accepted": 70},
    ]
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    out = cost_model_rows(str(path), preset="base", alpha_batch=1)
    assert len(out) == 2          # chain row AND tree row both priced
    tree = next(r for r in out if r.get("tree_branch") == 4)
    chain = next(r for r in out if "tree_branch" not in r)
    assert tree["tree_nodes"] == 1 + 2 * 4
    assert tree["measured_tokens_per_step"] == 1.95
    assert tree["drafter_free"] is True          # ngram = zero cost
    assert 0.0 < tree["est_alpha_primary"] < 1.0
    assert tree["est_tokens_per_step"] > 1.0
    # the tree window moves more bytes than the chain window at the
    # same depth, but buys more tokens per pass
    assert tree["model_bytes_iter"] > chain["model_bytes_iter"]
    assert isinstance(tree["clears_15pct"], bool)
