"""Decode-throughput bench harness smoke (tiny preset, CPU mesh)."""

from icikit.bench.decode import decode_bytes_per_token, run_bench


def test_decode_bench_tiny():
    rec = run_bench("tiny", dp=1, tp=1, batch=2, prompt_len=8, n_new=4,
                    runs=1)
    assert rec["unit"] == "tokens/s" and rec["value"] > 0
    assert rec["per_token_ms"] > 0
    assert rec["metric"].startswith("decode_tiny_")


def test_decode_bench_sampling_and_gqa():
    rec = run_bench("tiny", dp=1, tp=1, batch=2, prompt_len=8, n_new=4,
                    sampling="sample", runs=1, kv_heads=2)
    assert rec["value"] > 0


def test_decode_bytes_accounting():
    from icikit.bench.train import PRESETS
    from icikit.models.transformer import TransformerConfig
    cfg = TransformerConfig(**PRESETS["tiny"])
    b1 = decode_bytes_per_token(cfg, batch=1, cache_len=16)
    b2 = decode_bytes_per_token(cfg, batch=1, cache_len=32)
    assert b2 > b1  # longer cache reads more


def test_decode_bench_speculative():
    rec = run_bench("tiny", dp=1, tp=1, batch=2, prompt_len=8, n_new=8,
                    runs=1, speculate=3, draft_layers=1)
    assert rec["speculate"] == 3 and rec["draft_layers"] == 1
    assert 0.0 <= rec["acceptance_rate"] <= 1.0
    assert 1.0 <= rec["tokens_per_step"] <= 3.0
    # the acceptance × cost model rides on every speculative row
    assert rec["projected_eff_ms_per_token"] > 0
    assert "_spec3d1" in rec["metric"]
    assert rec["backend"]  # provenance: rows from CPU and TPU differ


def test_decode_bench_trained_drafter():
    """--drafter trained builds the draft branch (random-init) and the
    row carries the drafter tag — the wall-time machinery path."""
    rec = run_bench("tiny", dp=1, tp=1, batch=2, prompt_len=8, n_new=8,
                    runs=1, speculate=2, draft_layers=1,
                    drafter="trained")
    assert rec["drafter"] == "trained"
    assert "_spec2d1_trained" in rec["metric"]
    assert 0.0 <= rec["acceptance_rate"] <= 1.0


def test_cost_model_from_records(tmp_path):
    """The reproducible-verdict path: measured acceptance rows in,
    priced projection rows out — last row per (k, L_d, drafter) wins,
    depth fractions map onto the pricing preset."""
    import json
    from icikit.bench.decode import cost_model_rows
    path = tmp_path / "acc.jsonl"
    rows = [
        # superseded older measurement (lower α) — must NOT be priced
        {"kind": "acceptance", "batch": 1, "k": 2, "draft_layers": 1,
         "n_layers": 4, "drafter": "trained", "acceptance_rate": 0.10,
         "train_steps": 100},
        {"kind": "acceptance", "batch": 1, "k": 2, "draft_layers": 1,
         "n_layers": 4, "drafter": "trained", "acceptance_rate": 0.40,
         "train_steps": 3000},
        # r7-style row without a drafter field -> "shared"
        {"kind": "acceptance", "batch": 1, "k": 2, "draft_layers": 2,
         "n_layers": 4, "acceptance_rate": 0.15, "train_steps": 3000},
        # other batch: excluded at alpha_batch=1
        {"kind": "acceptance", "batch": 8, "k": 2, "draft_layers": 1,
         "n_layers": 4, "drafter": "trained", "acceptance_rate": 0.9},
    ]
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    out = cost_model_rows(str(path), preset="base", alpha_batch=1)
    assert len(out) == 2
    by = {(r["k"], r["draft_fraction"], r["drafter"]): r for r in out}
    tr = by[(2, 0.25, "trained")]
    assert tr["measured_acceptance"] == 0.40          # latest row won
    assert tr["draft_layers"] == 3                    # 12 * 0.25
    assert tr["alpha_train_steps"] == 3000
    # α=0.40 beats the ~0.336 quarter-depth break-even
    assert tr["measured_acceptance"] > tr["breakeven_acceptance"]
    assert tr["projected_eff_ms_per_token"] < tr["model_floor_ms"]
    sh = by[(2, 0.5, "shared")]
    assert sh["draft_layers"] == 6
    assert sh["measured_acceptance"] < sh["breakeven_acceptance"]


def test_cost_model_requires_acceptance_rows(tmp_path):
    import pytest
    from icikit.bench.decode import cost_model_rows
    path = tmp_path / "empty.jsonl"
    path.write_text("")
    with pytest.raises(ValueError, match="acceptance"):
        cost_model_rows(str(path))


def test_spec_breakeven_batch_rows():
    """Batch-aware pricing (ROADMAP 3c): rows exist per b, the b=1 row
    anchors on the committed measured floor, and the model's verdict
    shape holds — break-even α drifts DOWN with b (the truncated
    drafter re-reads only its depth fraction of the b-scaled cache)
    while the absolute baseline worsens."""
    from icikit.bench.decode import SPEC_FLOOR_MS, spec_breakeven_rows
    rows = spec_breakeven_rows(preset="base", batches=(1, 4, 16))
    assert len(rows) == 3 * 3 * 2     # b x k x frac
    by = {(r["batch"], r["k"], r["draft_fraction"]): r for r in rows}
    b1 = by[(1, 2, 0.25)]
    assert b1["baseline_source"] == "measured-floor"
    assert b1["baseline_ms_per_token"] == SPEC_FLOOR_MS
    # the b=1 break-even must agree with the r8 committed ~0.336
    assert abs(b1["breakeven_acceptance"] - 0.336) < 0.01
    for k in (2, 4, 8):
        for frac in (0.25, 0.5):
            be = [by[(b, k, frac)]["breakeven_acceptance"]
                  for b in (1, 4, 16)]
            assert be[0] >= be[1] >= be[2]          # drifts down
    base = [by[(b, 2, 0.25)]["baseline_ms_per_token"]
            for b in (1, 4, 16)]
    assert base[0] < base[1] < base[2]              # cache term grows
    for r in rows:
        assert r["kind"] == "breakeven"
        assert 0 < r["breakeven_acceptance"] \
            < r["breakeven_acceptance_15pct"]
        if r["batch"] > 1:
            assert r["baseline_source"] == "modeled"


def test_spec_cost_model_anchors():
    """At tokens_per_step = 1 and k = 1 the model must reproduce the
    baseline floor exactly (no drafts, one verify pass = one
    single-token step); more tokens per step must strictly help."""
    from icikit.bench.decode import spec_cost_model
    from icikit.bench.train import PRESETS
    from icikit.models.transformer import TransformerConfig
    cfg = TransformerConfig(**PRESETS["base"])
    m1 = spec_cost_model(cfg, 1, 320, k=1, draft_layers=6,
                         tokens_per_step=1.0)
    assert m1["projected_eff_ms_per_token"] == m1["model_floor_ms"]
    m2 = spec_cost_model(cfg, 1, 320, k=4, draft_layers=6,
                         tokens_per_step=3.0)
    m3 = spec_cost_model(cfg, 1, 320, k=4, draft_layers=6,
                         tokens_per_step=1.5)
    assert m2["projected_eff_ms_per_token"] < m3[
        "projected_eff_ms_per_token"]
