"""Decode-throughput bench harness smoke (tiny preset, CPU mesh)."""

from icikit.bench.decode import decode_bytes_per_token, run_bench


def test_decode_bench_tiny():
    rec = run_bench("tiny", dp=1, tp=1, batch=2, prompt_len=8, n_new=4,
                    runs=1)
    assert rec["unit"] == "tokens/s" and rec["value"] > 0
    assert rec["per_token_ms"] > 0
    assert rec["metric"].startswith("decode_tiny_")


def test_decode_bench_sampling_and_gqa():
    rec = run_bench("tiny", dp=1, tp=1, batch=2, prompt_len=8, n_new=4,
                    sampling="sample", runs=1, kv_heads=2)
    assert rec["value"] > 0


def test_decode_bytes_accounting():
    from icikit.bench.train import PRESETS
    from icikit.models.transformer import TransformerConfig
    cfg = TransformerConfig(**PRESETS["tiny"])
    b1 = decode_bytes_per_token(cfg, batch=1, cache_len=16)
    b2 = decode_bytes_per_token(cfg, batch=1, cache_len=32)
    assert b2 > b1  # longer cache reads more
