"""Decode-throughput bench harness smoke (tiny preset, CPU mesh)."""

from icikit.bench.decode import decode_bytes_per_token, run_bench


def test_decode_bench_tiny():
    rec = run_bench("tiny", dp=1, tp=1, batch=2, prompt_len=8, n_new=4,
                    runs=1)
    assert rec["unit"] == "tokens/s" and rec["value"] > 0
    assert rec["per_token_ms"] > 0
    assert rec["metric"].startswith("decode_tiny_")


def test_decode_bench_sampling_and_gqa():
    rec = run_bench("tiny", dp=1, tp=1, batch=2, prompt_len=8, n_new=4,
                    sampling="sample", runs=1, kv_heads=2)
    assert rec["value"] > 0


def test_decode_bytes_accounting():
    from icikit.bench.train import PRESETS
    from icikit.models.transformer import TransformerConfig
    cfg = TransformerConfig(**PRESETS["tiny"])
    b1 = decode_bytes_per_token(cfg, batch=1, cache_len=16)
    b2 = decode_bytes_per_token(cfg, batch=1, cache_len=32)
    assert b2 > b1  # longer cache reads more


def test_decode_bench_speculative():
    rec = run_bench("tiny", dp=1, tp=1, batch=2, prompt_len=8, n_new=8,
                    runs=1, speculate=3, draft_layers=1)
    assert rec["speculate"] == 3 and rec["draft_layers"] == 1
    assert 0.0 <= rec["acceptance_rate"] <= 1.0
    assert 1.0 <= rec["tokens_per_step"] <= 3.0
    # the acceptance × cost model rides on every speculative row
    assert rec["projected_eff_ms_per_token"] > 0
    assert "_spec3d1" in rec["metric"]
    assert rec["backend"]  # provenance: rows from CPU and TPU differ


def test_spec_cost_model_anchors():
    """At tokens_per_step = 1 and k = 1 the model must reproduce the
    baseline floor exactly (no drafts, one verify pass = one
    single-token step); more tokens per step must strictly help."""
    from icikit.bench.decode import spec_cost_model
    from icikit.bench.train import PRESETS
    from icikit.models.transformer import TransformerConfig
    cfg = TransformerConfig(**PRESETS["base"])
    m1 = spec_cost_model(cfg, 1, 320, k=1, draft_layers=6,
                         tokens_per_step=1.0)
    assert m1["projected_eff_ms_per_token"] == m1["model_floor_ms"]
    m2 = spec_cost_model(cfg, 1, 320, k=4, draft_layers=6,
                         tokens_per_step=3.0)
    m3 = spec_cost_model(cfg, 1, 320, k=4, draft_layers=6,
                         tokens_per_step=1.5)
    assert m2["projected_eff_ms_per_token"] < m3[
        "projected_eff_ms_per_token"]
