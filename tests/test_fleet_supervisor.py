"""Autoscale supervisor policy (`icikit.fleet.supervisor`): fakes +
a fake clock drive every decision path — no processes, no sockets.

The load-bearing claims:

- scale-up fires only on *new* watch alerts (the verdict is
  cumulative over the run; a stale alert must not read as permanent
  pressure), filtered to the configured metrics, bounded by the
  ceiling and the spawn cooldown;
- scale-down requires *sustained* idle (pending at zero, no alert),
  honors the floor and retire cooldown, and retires LIFO among the
  supervisor's OWN joiners only — the operator's base fleet is never
  scaled away;
- a coordinator failover (the watch restarts, the alert list
  shrinks) rebases the cursor instead of wedging or double-firing;
- the daemon loop outlives stats hiccups (a coordinator
  mid-failover must not kill the policy thread).
"""

import time

import pytest

from icikit.fleet.supervisor import Supervisor


class _Fleet:
    """Fake coordinator surface: mutable stats + spawn/retire logs."""

    def __init__(self, engines=("base0",)):
        self.engines = {e: "live" for e in engines}
        self.pending = 0
        self.alerts: list = []
        self.spawns: list = []
        self.retires: list = []

    def stats(self):
        return {"engines": {e: {"state": s}
                            for e, s in self.engines.items()},
                "pending": self.pending,
                "watch": {"alerts": list(self.alerts)}}

    def spawn(self, eid):
        self.spawns.append(eid)
        self.engines[eid] = "live"

    def retire(self, eid):
        self.retires.append(eid)
        self.engines[eid] = "retired"

    def alert(self, metric="fleet.pending"):
        self.alerts.append({"metric": metric})


def _sup(fleet, **kw):
    kw.setdefault("floor", 1)
    kw.setdefault("ceiling", 3)
    kw.setdefault("spawn_cooldown_s", 5.0)
    kw.setdefault("retire_cooldown_s", 5.0)
    kw.setdefault("scale_down_idle_s", 2.0)
    return Supervisor(fleet.stats, fleet.spawn, fleet.retire, **kw)


def test_alert_spawns_and_cooldown_bounds_thrash():
    f = _Fleet()
    sup = _sup(f)
    f.alert()
    ev = sup.tick(now=0.0)
    assert ev["action"] == "spawn" and ev["reason"] == "fleet.pending"
    assert f.spawns == ["auto0"]
    # a second alert while the first joiner is still compiling must
    # not spawn a second joiner inside the cooldown
    f.alert()
    assert sup.tick(now=1.0) is None
    f.alert()
    assert sup.tick(now=6.0)["action"] == "spawn"
    assert f.spawns == ["auto0", "auto1"]
    assert sup.n_spawns == 2


def test_cumulative_alert_list_is_not_sustained_pressure():
    """`Watch.verdict()` accumulates alerts over the run: the SAME
    old alert re-read every tick must not spawn-loop once per
    cooldown window — pressure is the alert *delta*."""
    f = _Fleet()
    sup = _sup(f)
    f.alert()
    assert sup.tick(now=0.0)["action"] == "spawn"
    f.pending = 1             # backlog keeps the idle path quiet
    assert sup.tick(now=10.0) is None
    assert sup.tick(now=20.0) is None
    assert f.spawns == ["auto0"]


def test_watch_restart_rebases_alert_cursor():
    f = _Fleet()
    sup = _sup(f)
    for _ in range(3):
        f.alert()
    assert sup.tick(now=0.0)["action"] == "spawn"
    # failover: the successor's watch starts fresh, the list SHRANK —
    # its first alert is new pressure, not history
    f.alerts = [{"metric": "fleet.pending"}]
    assert sup.tick(now=6.0)["action"] == "spawn"
    assert f.spawns == ["auto0", "auto1"]


def test_ceiling_bounds_scale_up():
    f = _Fleet(engines=("base0", "base1", "base2"))
    sup = _sup(f)           # ceiling 3, roster already there
    f.alert()
    assert sup.tick(now=0.0) is None
    assert f.spawns == []


def test_unlisted_alert_metrics_do_not_spawn():
    f = _Fleet()
    sup = _sup(f)
    f.alert(metric="serve.tpot_ms")    # not a scale-up signal
    assert sup.tick(now=0.0) is None
    assert f.spawns == []


def test_idle_retires_own_joiners_lifo_never_base_fleet():
    f = _Fleet()
    sup = _sup(f, retire_cooldown_s=0.0)
    for t in (0.0, 6.0):
        f.alert()
        assert sup.tick(now=t)["action"] == "spawn"
    assert f.spawns == ["auto0", "auto1"]
    # idleness must SUSTAIN scale_down_idle_s before the first retire
    assert sup.tick(now=12.0) is None
    assert sup.tick(now=13.0) is None
    ev = sup.tick(now=14.5)
    assert ev["action"] == "retire" and ev["engine"] == "auto1"
    # idleness re-observes from scratch after each retire
    assert sup.tick(now=14.6) is None
    assert sup.tick(now=17.0)["action"] == "retire"
    assert f.retires == ["auto1", "auto0"]
    # the floor holds and the base fleet is not ours to shrink
    assert sup.tick(now=30.0) is None
    assert sup.tick(now=33.0) is None
    assert "base0" not in f.retires
    assert sup.n_retires == 2


def test_pending_backlog_suppresses_idle_but_only_alerts_spawn():
    f = _Fleet()
    sup = _sup(f, retire_cooldown_s=0.0)
    f.alert()
    sup.tick(now=0.0)
    f.pending = 4
    assert sup.tick(now=10.0) is None      # backlog is not an alert…
    assert f.spawns == ["auto0"]
    assert sup.tick(now=20.0) is None      # …but it suppresses idle
    f.pending = 0
    assert sup.tick(now=30.0) is None      # idle clock starts here
    assert sup.tick(now=32.5)["action"] == "retire"


def test_retire_cooldown_spaces_scale_down():
    f = _Fleet()
    sup = _sup(f, spawn_cooldown_s=0.0, retire_cooldown_s=10.0,
               scale_down_idle_s=0.0)
    for t in (0.0, 1.0):
        f.alert()
        sup.tick(now=t)
    assert sup.tick(now=2.0)["action"] == "retire"
    assert sup.tick(now=5.0) is None       # cooling down
    assert sup.tick(now=12.5)["action"] == "retire"
    assert f.retires == ["auto1", "auto0"]


def test_timeline_is_a_copy_and_events_are_stamped():
    f = _Fleet()
    sup = _sup(f)
    f.alert()
    sup.tick(now=1.5)
    tl = sup.timeline()
    assert tl == [{"t": 1.5, "action": "spawn", "engine": "auto0",
                   "reason": "fleet.pending"}]
    tl[0]["action"] = "mutated"
    assert sup.timeline()[0]["action"] == "spawn"


def test_floor_ceiling_validation():
    f = _Fleet()
    with pytest.raises(ValueError):
        Supervisor(f.stats, f.spawn, f.retire, floor=3, ceiling=2)
    with pytest.raises(ValueError):
        Supervisor(f.stats, f.spawn, f.retire, floor=-1)
    with pytest.raises(ValueError):
        Supervisor(f.stats, f.spawn, f.retire, floor=0, ceiling=0)


def test_daemon_loop_survives_stats_hiccup_and_stops():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        raise ConnectionError("coordinator mid-failover")

    sup = Supervisor(flaky, lambda e: None, lambda e: None,
                     poll_s=0.01)
    sup.start()
    try:
        deadline = time.monotonic() + 5.0
        while calls["n"] < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
    finally:
        sup.stop()
    assert calls["n"] >= 3       # the loop outlived the exceptions
    assert sup._thread is not None and not sup._thread.is_alive()
