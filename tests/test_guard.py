"""Watchdog/trap lifecycle (`icikit.utils.guard`): disarm must undo
everything chopsigs installed, and the 1200 s reference budget must be
overridable per-queue via ICIKIT_WATCHDOG_S."""

import signal

import pytest

from icikit import native
from icikit.utils import guard


@pytest.fixture
def fake_native(monkeypatch):
    """Route guard through a recording fake of the native layer and
    force the Python-fallback trap path, so the test observes arming/
    disarming without installing real C signal handlers."""
    calls = []
    monkeypatch.setattr(native, "install_traps", lambda: False)
    monkeypatch.setattr(native, "restore_traps", lambda: True)
    monkeypatch.setattr(native, "watchdog", calls.append)
    # isolate from any previously saved fallback handler
    monkeypatch.setattr(guard, "_saved_py_alarm", guard._NO_SAVED)
    monkeypatch.setattr(guard, "_armed_timeout_s", None)
    return calls


def test_default_timeout_is_reference_budget(monkeypatch):
    monkeypatch.delenv("ICIKIT_WATCHDOG_S", raising=False)
    assert guard.default_timeout_s() == guard.DEFAULT_TIMEOUT_S == 1200


@pytest.mark.parametrize("raw,expect", [
    ("77", 77),
    ("0", guard.DEFAULT_TIMEOUT_S),      # non-positive: keep default
    ("-5", guard.DEFAULT_TIMEOUT_S),
    ("soon", guard.DEFAULT_TIMEOUT_S),   # garbage: keep default
    ("", guard.DEFAULT_TIMEOUT_S),
])
def test_watchdog_env_override(monkeypatch, raw, expect):
    monkeypatch.setenv("ICIKIT_WATCHDOG_S", raw)
    assert guard.default_timeout_s() == expect


@pytest.mark.parametrize("flag,raw,expect", [
    (30, "77", 30),     # explicit flag always wins
    (0, "77", 0),       # including 0 = off
    (None, "77", 77),   # no flag: a set env arms its value
    (None, None, 0),    # neither: off (CLIs opt in)
    (None, "0", 0),     # set-but-zero = off
    (None, "-5", 0),    # non-positive = off
    (None, "soon", 0),  # unparsable = off
])
def test_resolve_watchdog_s(monkeypatch, flag, raw, expect):
    if raw is None:
        monkeypatch.delenv("ICIKIT_WATCHDOG_S", raising=False)
    else:
        monkeypatch.setenv("ICIKIT_WATCHDOG_S", raw)
    assert guard.resolve_watchdog_s(flag) == expect


def test_chopsigs_arms_env_budget(fake_native, monkeypatch):
    monkeypatch.setenv("ICIKIT_WATCHDOG_S", "345")
    try:
        assert guard.chopsigs() is False  # fallback path forced
        assert fake_native == [345]
        assert guard.armed_timeout_s() == 345
    finally:
        guard.disarm()
    assert fake_native == [345, 0]       # disarm cancelled the alarm
    assert guard.armed_timeout_s() is None


def test_explicit_timeout_beats_env(fake_native, monkeypatch):
    monkeypatch.setenv("ICIKIT_WATCHDOG_S", "345")
    try:
        guard.chopsigs(timeout_s=9)
        assert fake_native == [9]
    finally:
        guard.disarm()


def test_disarm_restores_python_alarm_disposition(fake_native):
    """The fallback SIGALRM handler chopsigs installs must be exactly
    undone by disarm — a guarded run that finished must leave the
    process's signal table as it found it."""
    before = signal.getsignal(signal.SIGALRM)
    guard.chopsigs(timeout_s=30)
    installed = signal.getsignal(signal.SIGALRM)
    assert installed is not before and callable(installed)
    with pytest.raises(TimeoutError):
        installed(signal.SIGALRM, None)  # the watchdog's exception
    guard.disarm()
    assert signal.getsignal(signal.SIGALRM) is before
    # idempotent: a second disarm must not clobber anything
    guard.disarm()
    assert signal.getsignal(signal.SIGALRM) is before


def test_nested_chopsigs_restores_pre_first_snapshot(fake_native):
    """Re-arming without disarming (CLI calls chopsigs, then a library
    call does too) must still restore the ORIGINAL disposition."""
    before = signal.getsignal(signal.SIGALRM)
    guard.chopsigs(timeout_s=30)
    guard.chopsigs(timeout_s=60)  # saved snapshot must not be clobbered
    guard.disarm()
    assert signal.getsignal(signal.SIGALRM) is before
