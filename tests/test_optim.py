"""Optimizer-construction tests: schedules, clipping, accumulation.

The key oracle: ``accum_steps=k`` over k equal microbatches produces
the same parameters as one step on the concatenated batch (the loss is
a per-token mean, so the mean-of-microbatch-grads equals the big-batch
grad)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from icikit.models.transformer import (
    TransformerConfig,
    init_params,
    make_train_step,
)
from icikit.models.transformer.model import make_model_mesh
from icikit.models.transformer.optim import make_optimizer, make_schedule


def _cfg():
    return TransformerConfig(vocab=32, d_model=16, n_heads=2, d_head=8,
                             d_ff=32, n_layers=1, max_seq=8,
                             compute_dtype="float32")


def _tokens(b, s, seed):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, 32, (b, s)), jnp.int32)


def test_schedule_shapes():
    s = make_schedule(1.0, "warmup_cosine", warmup_steps=10,
                      total_steps=100)
    assert float(s(0)) == 0.0
    assert float(s(10)) == pytest.approx(1.0)
    assert float(s(100)) == pytest.approx(0.0, abs=1e-6)
    s = make_schedule(2.0, "warmup_linear", warmup_steps=4,
                      total_steps=8, min_lr_ratio=0.5)
    assert float(s(4)) == pytest.approx(2.0)
    assert float(s(8)) == pytest.approx(1.0)
    const = make_schedule(3e-4, "constant")
    assert const == 3e-4


def test_schedule_validation():
    with pytest.raises(ValueError, match="unknown schedule"):
        make_schedule(1.0, "exponential")
    with pytest.raises(ValueError, match="total_steps"):
        make_schedule(1.0, "warmup_cosine", warmup_steps=10,
                      total_steps=10)


def test_grad_clip_bounds_update():
    """With an absurdly small clip norm the global update norm is
    bounded by clip * lr (Adam normalizes per-coordinate, so check the
    clip actually engaged by comparing against the unclipped run)."""
    cfg = _cfg()
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    tok, tgt = _tokens(2, 8, 0), _tokens(2, 8, 1)

    def run(tx):
        params = init_params(jax.random.key(0), cfg, mesh)
        _, step = make_train_step(mesh, cfg, tx)
        opt_state = tx.init(params)
        new_params, _, _ = step(params, opt_state, tok, tgt)
        return jax.tree.map(lambda a, b: np.abs(np.asarray(a - b)).max(),
                            new_params, params)

    moved_clipped = run(make_optimizer(1e-2, grad_clip=1e-6))
    moved_free = run(make_optimizer(1e-2))
    total_c = max(jax.tree.leaves(moved_clipped))
    total_f = max(jax.tree.leaves(moved_free))
    assert total_c < total_f  # the clip engaged


def test_accumulation_matches_big_batch():
    cfg = _cfg()
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    b1 = (_tokens(2, 8, 2), _tokens(2, 8, 3))
    b2 = (_tokens(2, 8, 4), _tokens(2, 8, 5))
    big = (jnp.concatenate([b1[0], b2[0]]), jnp.concatenate([b1[1], b2[1]]))

    params0 = init_params(jax.random.key(1), cfg, mesh)

    tx_acc = make_optimizer(1e-2, accum_steps=2)
    _, step_acc = make_train_step(mesh, cfg, tx_acc)
    st = tx_acc.init(params0)
    p_mid, st, _ = step_acc(params0, st, *b1)
    # microbatch 1 must not move the parameters
    same = jax.tree.map(lambda a, b: np.array_equal(np.asarray(a),
                                                    np.asarray(b)),
                        p_mid, params0)
    assert all(jax.tree.leaves(same))
    p_acc, st, _ = step_acc(p_mid, st, *b2)

    tx_big = make_optimizer(1e-2)
    _, step_big = make_train_step(mesh, cfg, tx_big)
    p_big, _, _ = step_big(params0, tx_big.init(params0), *big)

    for a, b in zip(jax.tree.leaves(p_acc), jax.tree.leaves(p_big)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)


def test_weight_decay_shrinks_params():
    """AdamW decay pulls an untouched-gradient direction toward zero:
    compare total parameter norm after identical steps with/without."""
    cfg = _cfg()
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    tok, tgt = _tokens(2, 8, 6), _tokens(2, 8, 7)

    def norm_after(tx):
        params = init_params(jax.random.key(2), cfg, mesh)
        _, step = make_train_step(mesh, cfg, tx)
        p, _, _ = step(params, tx.init(params), tok, tgt)
        return float(sum(np.square(np.asarray(x)).sum()
                         for x in jax.tree.leaves(p)))

    assert (norm_after(make_optimizer(1e-3, weight_decay=0.5))
            < norm_after(make_optimizer(1e-3)))


_CLI_BASE = ["--batch", "2", "--seq", "16", "--vocab", "64",
             "--d-model", "16", "--n-heads", "2", "--d-head", "8",
             "--d-ff", "32", "--n-layers", "1", "--log-every", "2",
             "--sample-tokens", "0"]


def test_trainer_cli_flags(tmp_path):
    """The CLI accepts the new knobs end-to-end, checkpoints, and
    resumes with the same optimizer structure."""
    from icikit.models.transformer.train import train
    flags = ["--lr-schedule", "warmup_cosine", "--warmup-steps", "1",
             "--grad-clip", "1.0", "--accum-steps", "2",
             "--weight-decay", "0.01",
             "--ckpt-dir", str(tmp_path / "run")]
    assert train(["--steps", "4", *_CLI_BASE, *flags]) == 0
    assert train(["--steps", "8", *_CLI_BASE, *flags]) == 0  # resume


def test_trainer_resume_rejects_structural_flag_change(tmp_path):
    """Changing a structure-affecting optimizer flag across a resume
    fails fast with the cause instead of an Orbax tree mismatch."""
    from icikit.models.transformer.train import train
    ckpt = ["--ckpt-dir", str(tmp_path / "run")]
    assert train(["--steps", "2", *_CLI_BASE, *ckpt]) == 0
    rc = train(["--steps", "4", *_CLI_BASE, *ckpt,
                "--accum-steps", "2"])
    assert rc == 2
    # non-structural change only warns
    rc = train(["--steps", "4", *_CLI_BASE, *ckpt, "--lr", "1e-3"])
    assert rc == 0


@pytest.mark.parametrize("use_pallas", [False, True])
def test_fused_adam_matches_optax(use_pallas):
    """The one-pass fused Adam (icikit.ops.adam) reproduces optax.adam
    step-for-step: same params after several steps from identical
    grads — both the XLA formulation (the step default) and the Pallas
    kernel path (interpret mode on CPU; lane-divisible leaves run the
    kernel, ragged ones the fallback)."""
    import optax

    cfg = _cfg()
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), cfg, mesh)
    tok, tgt = _tokens(2, 8, 1), _tokens(2, 8, 2)

    from icikit.models.transformer import FusedAdam
    opt_a, step_a = make_train_step(mesh, cfg, optax.adam(1e-3))
    opt_f, step_f = make_train_step(
        mesh, cfg, FusedAdam(1e-3, use_pallas=use_pallas))
    sa, sf = opt_a.init(params), opt_f.init(params)
    pa = pf = params
    for i in range(3):
        pa, sa, loss_a = step_a(pa, sa, tok, tgt)
        pf, sf, loss_f = step_f(pf, sf, tok, tgt)
    np.testing.assert_allclose(float(loss_a), float(loss_f),
                               rtol=1e-6)
    for k in pa:
        np.testing.assert_allclose(np.asarray(pa[k]), np.asarray(pf[k]),
                                   rtol=2e-6, atol=2e-7, err_msg=k)
    # moments too: m/v trees must match optax's mu/nu (atol spans the
    # cross-jit fusion flutter on near-zero gradient elements — the
    # two step programs compile separately, and the r6 constant-shift
    # forward gives XLA more reassociation freedom)
    mu, nu = sa[0].mu, sa[0].nu
    for k in mu:
        np.testing.assert_allclose(np.asarray(mu[k]),
                                   np.asarray(sf[0][k]),
                                   rtol=2e-6, atol=2e-7, err_msg=k)
        np.testing.assert_allclose(np.asarray(nu[k]),
                                   np.asarray(sf[1][k]),
                                   rtol=2e-6, atol=1e-9, err_msg=k)


def test_fused_adam_bf16_moments_state_dtypes_and_first_steps():
    """bf16-moment FusedAdam (r5 optimizer-stream A/B): state dtypes
    honor mu/nu_dtype, and early steps track the fp32-moment run
    closely (the storage rounding is the only divergence source —
    update arithmetic stays fp32)."""
    cfg = _cfg()
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), cfg, mesh)
    tok, tgt = _tokens(2, 8, 1), _tokens(2, 8, 2)

    from icikit.models.transformer import FusedAdam
    opt_a, step_a = make_train_step(mesh, cfg, FusedAdam(1e-3))
    opt_b, step_b = make_train_step(
        mesh, cfg, FusedAdam(1e-3, mu_dtype=jnp.bfloat16,
                             nu_dtype=jnp.bfloat16))
    sa, sb = opt_a.init(params), opt_b.init(params)
    for k, leaf in sb[0].items():
        if jnp.issubdtype(params[k].dtype, jnp.floating):
            assert leaf.dtype == jnp.bfloat16, k
            assert sb[1][k].dtype == jnp.bfloat16, k
    pa = pb = params
    for _ in range(3):
        pa, sa, loss_a = step_a(pa, sa, tok, tgt)
        pb, sb, loss_b = step_b(pb, sb, tok, tgt)
    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-3)
    for k in pa:
        np.testing.assert_allclose(np.asarray(pa[k], np.float32),
                                   np.asarray(pb[k], np.float32),
                                   rtol=5e-3, atol=5e-4, err_msg=k)


def test_fused_adam_pallas_bf16_moments_matches_xla():
    """The pallas + bf16-moments combination (r6 satellite): kernel-
    covered leaves reproduce the XLA one-pass update bit-for-bit-close,
    and sublane-ragged leaves (rows % 16 != 0 with bf16 operands) take
    the XLA fallback instead of handing Mosaic an untileable block."""
    from icikit.ops.adam import _use_pallas, adam_apply

    rng = np.random.default_rng(3)

    def leaves(shape):
        p = {"w": jnp.asarray(rng.normal(size=shape), jnp.float32)}
        m = {"w": jnp.asarray(rng.normal(size=shape) * 0.1, jnp.bfloat16)}
        v = {"w": jnp.asarray(rng.random(shape) * 0.01, jnp.bfloat16)}
        g = {"w": jnp.asarray(rng.normal(size=shape), jnp.bfloat16)}
        return p, m, v, g

    # covered: 32 rows of 128 lanes satisfies the bf16 sublane rule
    p, m, v, g = leaves((32, 128))
    assert _use_pallas(p["w"], m["w"], v["w"], g["w"])
    out_pl = adam_apply(p, m, v, g, 1e-3, jnp.int32(2), use_pallas=True)
    out_xla = adam_apply(p, m, v, g, 1e-3, jnp.int32(2), use_pallas=False)
    # params update in fp32 — tight; bf16 moment stores may differ by
    # one ulp where the kernel's fused multiply-add and XLA's unfused
    # chain land on opposite sides of a rounding tie
    np.testing.assert_allclose(np.asarray(out_pl[0]["w"]),
                               np.asarray(out_xla[0]["w"]),
                               rtol=1e-6, atol=1e-7)
    for a, b in ((out_pl[1]["w"], out_xla[1]["w"]),
                 (out_pl[2]["w"], out_xla[2]["w"])):
        assert a.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=1e-2, atol=1e-3)

    # ragged: 24 rows breaks the bf16 sublane rule (fine for fp32) —
    # the gate must route it to the fallback, and the whole-tree API
    # must still produce the right numbers
    p, m, v, g = leaves((24, 128))
    assert not _use_pallas(p["w"], m["w"], v["w"], g["w"])
    assert _use_pallas(p["w"], p["w"], p["w"], p["w"])  # fp32: rows%8
    out_pl = adam_apply(p, m, v, g, 1e-3, jnp.int32(2), use_pallas=True)
    out_xla = adam_apply(p, m, v, g, 1e-3, jnp.int32(2), use_pallas=False)
    for a, b in zip(jax.tree.leaves(out_pl), jax.tree.leaves(out_xla)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=1e-5, atol=1e-6)


def test_fused_adam_kernel_leaf_matches_reference():
    """Direct kernel check on a lane-divisible leaf: one fused update
    equals the reference formula in fp64-ish (fp32) math, including
    bias correction at t=1 and a bf16 gradient."""
    from icikit.ops.adam import adam_apply

    rng = np.random.default_rng(0)
    shape = (16, 128)
    p = {"w": jnp.asarray(rng.normal(size=shape), jnp.float32)}
    m = {"w": jnp.asarray(rng.normal(size=shape) * 0.1, jnp.float32)}
    v = {"w": jnp.asarray(rng.random(shape) * 0.01, jnp.float32)}
    g = {"w": jnp.asarray(rng.normal(size=shape), jnp.bfloat16)}
    lr, b1, b2, eps = 1e-3, 0.9, 0.999, 1e-8
    po, mo, vo = jax.jit(
        lambda p, m, v, g: adam_apply(p, m, v, g, lr, jnp.int32(1),
                                      b1, b2, eps))(p, m, v, g)
    gf = np.asarray(g["w"], np.float32)
    m_ref = np.asarray(m["w"]) * b1 + gf * (1 - b1)
    v_ref = np.asarray(v["w"]) * b2 + gf * gf * (1 - b2)
    mhat = m_ref / (1 - b1)
    vhat = v_ref / (1 - b2)
    p_ref = np.asarray(p["w"]) - lr * mhat / (np.sqrt(vhat) + eps)
    # fma contraction + hw divide/sqrt approximations differ from
    # numpy by a few ulp; the oracle is formula shape, not bit equality
    np.testing.assert_allclose(np.asarray(mo["w"]), m_ref, rtol=1e-5,
                               atol=1e-7)
    np.testing.assert_allclose(np.asarray(vo["w"]), v_ref, rtol=1e-5,
                               atol=1e-8)
    np.testing.assert_allclose(np.asarray(po["w"]), p_ref, rtol=1e-5,
                               atol=1e-7)


def test_fused_adam_sharded_matches_optax():
    """FusedAdam's shard_map update (per-leaf param specs, replicated
    scalars) agrees with optax on a dp=2 x tp=2 x sp=2 mesh — the
    multi-chip path the dryrun exercises."""
    import optax

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device simulated mesh")
    cfg = dataclasses.replace(_cfg(), d_model=32, n_heads=4, d_head=8)
    mesh = make_model_mesh(dp=2, tp=2, sp=2)
    params = init_params(jax.random.key(0), cfg, mesh)
    tok, tgt = _tokens(4, 8, 1), _tokens(4, 8, 2)

    from icikit.models.transformer import FusedAdam
    opt_a, step_a = make_train_step(mesh, cfg, optax.adam(1e-3))
    opt_f, step_f = make_train_step(mesh, cfg, FusedAdam(1e-3))
    sa, sf = opt_a.init(params), opt_f.init(params)
    pa = pf = params
    for _ in range(2):
        pa, sa, loss_a = step_a(pa, sa, tok, tgt)
        pf, sf, loss_f = step_f(pf, sf, tok, tgt)
    np.testing.assert_allclose(float(loss_a), float(loss_f), rtol=1e-6)
    for k in pa:
        np.testing.assert_allclose(np.asarray(pa[k]), np.asarray(pf[k]),
                                   rtol=2e-6, atol=2e-7, err_msg=k)
