"""Decoding tests: the KV-cache greedy decode must reproduce the
token-by-token full-re-forward argmax continuation (the O(T^2) oracle),
on a single device and tensor-parallel meshes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from icikit.models.transformer import TransformerConfig, init_params
from icikit.models.transformer.decode import greedy_generate
from icikit.models.transformer.model import make_model_mesh

CFG = TransformerConfig(vocab=61, d_model=32, n_heads=4, d_head=8,
                        d_ff=64, n_layers=2, max_seq=24,
                        compute_dtype="float32")


def _oracle_continue(params, prompt, n_new):
    """Re-run the full causal forward for every new token (dense math,
    no shard_map, mirroring test_transformer's independent oracle)."""
    from icikit.models.attention.dense import dense_attention
    from icikit.models.transformer.model import _rms_norm

    p = {k: jnp.asarray(np.asarray(v)) for k, v in params.items()}
    toks = jnp.asarray(prompt)
    for _ in range(n_new):
        s = toks.shape[1]
        x = p["emb"][toks] + p["pos"][:s]
        for li in range(CFG.n_layers):
            h = _rms_norm(x, p["ln1"][li])
            qkv = jnp.einsum("bsd,dthe->bsthe", h, p["wqkv"][li])
            attn = dense_attention(qkv[:, :, 0], qkv[:, :, 1],
                                   qkv[:, :, 2], causal=True)
            x = x + jnp.einsum("bshe,hed->bsd", attn, p["wo"][li])
            h2 = _rms_norm(x, p["ln2"][li])
            u = jax.nn.gelu(jnp.einsum("bsd,df->bsf", h2, p["w1"][li]))
            x = x + jnp.einsum("bsf,fd->bsd", u, p["w2"][li])
        x = _rms_norm(x, p["ln_f"])
        logits = jnp.einsum("bd,vd->bv", x[:, -1], p["w_out"])
        nxt = jnp.argmax(logits, axis=-1).astype(toks.dtype)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    return np.asarray(toks)


@pytest.mark.parametrize("dp,tp", [(1, 1), (1, 4), (2, 2)])
def test_greedy_decode_matches_reforward_oracle(dp, tp):
    mesh = make_model_mesh(dp=dp, tp=tp, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, CFG.vocab, (4, 8)).astype(np.int32)
    pd = jax.device_put(jnp.asarray(prompt),
                        NamedSharding(mesh, P("dp", None)))
    got = np.asarray(greedy_generate(params, pd, mesh, CFG, n_new=6))
    want = _oracle_continue(params, prompt, 6)
    np.testing.assert_array_equal(got, want)


def test_decode_validation():
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    long_prompt = jnp.zeros((1, 20), jnp.int32)
    with pytest.raises(ValueError):
        greedy_generate(params, long_prompt, mesh, CFG, n_new=8)  # > max_seq
    sp_mesh = make_model_mesh(dp=1, tp=1, sp=2)
    with pytest.raises(ValueError):
        greedy_generate(params, jnp.zeros((1, 4), jnp.int32), sp_mesh,
                        CFG, n_new=2)

def test_sample_topk1_equals_greedy():
    from icikit.models.transformer.decode import sample_generate
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    prompt = jnp.asarray(np.arange(6)[None] % CFG.vocab, jnp.int32)
    pd = jax.device_put(prompt, NamedSharding(mesh, P("dp", None)))
    greedy = greedy_generate(params, pd, mesh, CFG, n_new=5)
    topk1 = sample_generate(params, pd, mesh, CFG, n_new=5,
                            key=jax.random.key(7), top_k=1)
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(topk1))
    # tiny nucleus keeps only the argmax too
    tp = sample_generate(params, pd, mesh, CFG, n_new=5,
                         key=jax.random.key(7), top_p=1e-6)
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(tp))


def test_sample_reproducible_and_key_sensitive():
    from icikit.models.transformer.decode import sample_generate
    mesh = make_model_mesh(dp=2, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, CFG.vocab, (4, 6)).astype(np.int32)
    pd = jax.device_put(jnp.asarray(prompt),
                        NamedSharding(mesh, P("dp", None)))
    a = np.asarray(sample_generate(params, pd, mesh, CFG, n_new=8,
                                   key=jax.random.key(1), temperature=1.5))
    b = np.asarray(sample_generate(params, pd, mesh, CFG, n_new=8,
                                   key=jax.random.key(1), temperature=1.5))
    c = np.asarray(sample_generate(params, pd, mesh, CFG, n_new=8,
                                   key=jax.random.key(2), temperature=1.5))
    np.testing.assert_array_equal(a, b)          # same key reproduces
    assert not np.array_equal(a, c)              # different key differs
    assert a.shape == (4, 14)
    assert ((a >= 0) & (a < CFG.vocab)).all()
    np.testing.assert_array_equal(a[:, :6], prompt)


def test_sample_rows_draw_independently():
    from icikit.models.transformer.decode import sample_generate
    mesh = make_model_mesh(dp=2, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    # identical prompt on every row: rows must still sample different
    # continuations — r12: via per-row SEED streams (default
    # seeds=arange(b)), not physical placement (tests/test_sampled.py
    # pins the placement-invariance side)
    prompt = np.broadcast_to(np.arange(6, dtype=np.int32), (4, 6)).copy()
    pd = jax.device_put(jnp.asarray(prompt),
                        NamedSharding(mesh, P("dp", None)))
    out = np.asarray(sample_generate(params, pd, mesh, CFG, n_new=10,
                                     key=jax.random.key(0),
                                     temperature=2.0))
    assert not np.array_equal(out[0], out[2])


def test_sample_validation():
    from icikit.models.transformer.decode import sample_generate
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    pd = jnp.zeros((1, 4), jnp.int32)
    with pytest.raises(ValueError, match="top_p"):
        sample_generate(params, pd, mesh, CFG, 2, jax.random.key(0),
                        top_p=0.0)
    with pytest.raises(ValueError, match="temperature"):
        sample_generate(params, pd, mesh, CFG, 2, jax.random.key(0),
                        temperature=-1.0)


# ------------------------------------------------ fused decode step

def _fused_cfg(**over):
    """d_head = 128 (the kernel's lane width) so the fused gate
    accepts; everything else tiny for the CPU interpreter."""
    base = dict(vocab=61, d_model=64, n_heads=2, d_head=128, d_ff=96,
                n_layers=2, max_seq=24, compute_dtype="float32")
    base.update(over)
    return base


def test_fused_decode_step_kernel_parity():
    """decode_step_attention == rope + cache dus + masked attention,
    on both the attention output and the written cache columns."""
    from jax import lax

    from icikit.models.transformer.decode import _masked_attention
    from icikit.ops.flash_attention import decode_step_attention
    from icikit.ops.rope import apply_rope, rope_sincos

    rng = np.random.default_rng(0)
    b, h, dh, total, cur = 2, 3, 128, 16, 5
    mk = lambda: jnp.asarray(rng.normal(size=(b, 1, h, dh)), jnp.float32)
    q, k, v = mk(), mk(), mk()
    kc = jnp.asarray(rng.normal(size=(b, total, h, dh)), jnp.float32)
    vc = jnp.asarray(rng.normal(size=(b, total, h, dh)), jnp.float32)
    scale = dh ** -0.5
    pos = jnp.asarray([cur])
    sc = rope_sincos(pos, dh, 10000.0)
    qr = apply_rope(q, pos, 10000.0, sc)
    kr = apply_rope(k, pos, 10000.0, sc)
    ks = lax.dynamic_update_slice_in_dim(kc, kr, cur, 1)
    vs = lax.dynamic_update_slice_in_dim(vc, v, cur, 1)
    mask = jnp.arange(total) <= cur
    want = _masked_attention(qr, ks, vs, mask, scale, 1)

    flat = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, *x.shape[3:])
    cos2 = jnp.concatenate([sc[0], sc[0]], -1)
    sin2 = jnp.concatenate([sc[1], sc[1]], -1)
    attn, kc2, vc2 = decode_step_attention(
        flat(q), flat(k), flat(v),
        kc.transpose(0, 2, 1, 3).reshape(b * h, total, dh),
        vc.transpose(0, 2, 1, 3).reshape(b * h, total, dh),
        jnp.int32(cur), cos2, sin2, scale=scale, rope=True)
    got = attn.reshape(b, h, 1, dh).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5)
    got_ks = kc2.reshape(b, h, total, dh).transpose(0, 2, 1, 3)
    got_vs = vc2.reshape(b, h, total, dh).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(np.asarray(got_ks), np.asarray(ks),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(got_vs), np.asarray(vs),
                               atol=1e-5)


@pytest.mark.parametrize("pos_encoding", ["rope", "learned"])
def test_fused_decode_generate_matches_unfused(pos_encoding):
    cfg_u = TransformerConfig(**_fused_cfg(pos_encoding=pos_encoding),
                              decode_step="unfused")
    cfg_f = TransformerConfig(**_fused_cfg(pos_encoding=pos_encoding),
                              decode_step="fused")
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), cfg_u, mesh)
    rng = np.random.default_rng(0)
    pd = jax.device_put(
        jnp.asarray(rng.integers(0, 61, (2, 8)), jnp.int32),
        NamedSharding(mesh, P("dp", None)))
    a = np.asarray(greedy_generate(params, pd, mesh, cfg_u, n_new=6))
    b = np.asarray(greedy_generate(params, pd, mesh, cfg_f, n_new=6))
    np.testing.assert_array_equal(a, b)


def test_fused_decode_gate_rejects_loudly():
    # CFG has d_head=8: forcing the fused step must fail, not fall
    # back (an A/B that silently measured the fallback would lie)
    cfg = TransformerConfig(vocab=61, d_model=32, n_heads=4, d_head=8,
                            d_ff=64, n_layers=2, max_seq=24,
                            compute_dtype="float32",
                            decode_step="fused")
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), cfg, mesh)
    with pytest.raises(ValueError, match="decode_step='fused'"):
        greedy_generate(params, jnp.zeros((1, 4), jnp.int32), mesh,
                        cfg, n_new=2)
