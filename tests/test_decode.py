"""Decoding tests: the KV-cache greedy decode must reproduce the
token-by-token full-re-forward argmax continuation (the O(T^2) oracle),
on a single device and tensor-parallel meshes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from icikit.models.transformer import TransformerConfig, init_params
from icikit.models.transformer.decode import greedy_generate
from icikit.models.transformer.model import make_model_mesh

CFG = TransformerConfig(vocab=61, d_model=32, n_heads=4, d_head=8,
                        d_ff=64, n_layers=2, max_seq=24,
                        compute_dtype="float32")


def _oracle_continue(params, prompt, n_new):
    """Re-run the full causal forward for every new token (dense math,
    no shard_map, mirroring test_transformer's independent oracle)."""
    from icikit.models.attention.dense import dense_attention
    from icikit.models.transformer.model import _rms_norm

    p = {k: jnp.asarray(np.asarray(v)) for k, v in params.items()}
    toks = jnp.asarray(prompt)
    for _ in range(n_new):
        s = toks.shape[1]
        x = p["emb"][toks] + p["pos"][:s]
        for li in range(CFG.n_layers):
            h = _rms_norm(x, p["ln1"][li])
            qkv = jnp.einsum("bsd,dthe->bsthe", h, p["wqkv"][li])
            attn = dense_attention(qkv[:, :, 0], qkv[:, :, 1],
                                   qkv[:, :, 2], causal=True)
            x = x + jnp.einsum("bshe,hed->bsd", attn, p["wo"][li])
            h2 = _rms_norm(x, p["ln2"][li])
            u = jax.nn.gelu(jnp.einsum("bsd,df->bsf", h2, p["w1"][li]))
            x = x + jnp.einsum("bsf,fd->bsd", u, p["w2"][li])
        x = _rms_norm(x, p["ln_f"])
        logits = jnp.einsum("bd,dv->bv", x[:, -1], p["w_out"])
        nxt = jnp.argmax(logits, axis=-1).astype(toks.dtype)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    return np.asarray(toks)


@pytest.mark.parametrize("dp,tp", [(1, 1), (1, 4), (2, 2)])
def test_greedy_decode_matches_reforward_oracle(dp, tp):
    mesh = make_model_mesh(dp=dp, tp=tp, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, CFG.vocab, (4, 8)).astype(np.int32)
    pd = jax.device_put(jnp.asarray(prompt),
                        NamedSharding(mesh, P("dp", None)))
    got = np.asarray(greedy_generate(params, pd, mesh, CFG, n_new=6))
    want = _oracle_continue(params, prompt, 6)
    np.testing.assert_array_equal(got, want)


def test_decode_validation():
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    long_prompt = jnp.zeros((1, 20), jnp.int32)
    with pytest.raises(ValueError):
        greedy_generate(params, long_prompt, mesh, CFG, n_new=8)  # > max_seq
    sp_mesh = make_model_mesh(dp=1, tp=1, sp=2)
    with pytest.raises(ValueError):
        greedy_generate(params, jnp.zeros((1, 4), jnp.int32), sp_mesh,
                        CFG, n_new=2)