"""Request-level chaos drills for the serving engine.

The three failure stories ROADMAP item 1 named, each drilled
end-to-end with the real detection/recovery machinery (no test-only
shortcuts):

- **dead-request abandonment** — an engine dies mid-serve; its leased
  requests expire and a second engine pointed at the same queue
  reissues and completes them, token-identically;
- **poisoned prompt** — a prompt corrupted between submit and
  admission trips the submit-time checksum, is rejected without
  retry, and the engine keeps serving everyone else;
- **KV-page corruption containment** — a bit flipped in a sealed KV
  page fails its *owning* request's completion verify (retry on
  fresh blocks succeeds) while co-batched requests' outputs stay
  bitwise what the unarmed baseline produces. Containment is
  structural — no other request's block table maps the page — and
  the drill proves it by outputs, not by construction claims.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from icikit import chaos
from icikit.models.transformer import (
    TransformerConfig,
    greedy_generate,
    init_params,
)
from icikit.models.transformer.model import make_model_mesh
from icikit.serve import Engine, RequestQueue, ServeConfig

CFG = TransformerConfig(vocab=61, d_model=32, n_heads=2, d_head=8,
                        d_ff=64, n_layers=2, max_seq=64,
                        compute_dtype="float32")


def _setup(n=2, seed=1, **over):
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, CFG.vocab, (8,)).astype(np.int32)
               for _ in range(n)]
    bases = [np.asarray(greedy_generate(
        params, jnp.asarray(p)[None], mesh, CFG, 10))[0, 8:]
        for p in prompts]
    sv = dict(max_rows=2, block_size=4, n_blocks=32, max_prompt=16,
              max_new=16)
    sv.update(over)
    return mesh, params, ServeConfig(**sv), prompts, bases


def test_dead_engine_abandonment_reissues_to_survivor():
    mesh, params, sv, prompts, bases = _setup()
    q = RequestQueue(lease_s=0.05)
    eng1 = Engine(params, mesh, CFG, sv, queue=q)
    rids = [eng1.submit(p, 10) for p in prompts]
    plan = chaos.FaultPlan(schedule={"die:serve.step": (0,)})
    with chaos.inject(plan):
        with pytest.raises(chaos.InjectedDeath):
            eng1.run()   # dies at the first step; leases dangle
        assert not q.drained() and len(q.done) == 0
        time.sleep(0.06)                     # outlive the leases
        eng2 = Engine(params, mesh, CFG, sv, queue=q)
        eng2.run()                           # reap -> reissue -> done
    assert q.n_reissues == len(rids)
    for rid, base in zip(rids, bases):
        req = q.request(rid)
        assert req.state == "done" and req.attempts == 2
        np.testing.assert_array_equal(np.asarray(req.tokens), base)


def test_poisoned_prompt_rejected_without_retry():
    mesh, params, sv, prompts, bases = _setup()
    eng = Engine(params, mesh, CFG, sv)
    rids = [eng.submit(p, 10) for p in prompts]
    plan = chaos.FaultPlan(
        schedule={"corrupt:serve.admit.prompt": (0,)})
    with chaos.inject(plan):
        eng.run()
    assert plan.fired("corrupt", "serve.admit.prompt") == 1
    bad = eng.queue.request(rids[0])         # FIFO: first claim hit
    assert bad.state == "failed" and bad.attempts == 1
    assert "Poisoned" in bad.error or "checksum" in bad.error
    ok = eng.queue.request(rids[1])
    assert ok.state == "done"
    np.testing.assert_array_equal(np.asarray(ok.tokens), bases[1])


def test_kv_page_corruption_contained_to_owner():
    mesh, params, sv, prompts, bases = _setup(integrity="pages")
    eng = Engine(params, mesh, CFG, sv)
    rids = [eng.submit(p, 10) for p in prompts]
    plan = chaos.FaultPlan(schedule={"corrupt:serve.kv.page": (0,)})
    with chaos.inject(plan):
        eng.run()
    assert plan.fired("corrupt", "serve.kv.page") == 1
    victim = eng.queue.request(rids[0])      # slot order: first probed
    other = eng.queue.request(rids[1])
    # the victim FAILED its integrity verify and retried on fresh
    # blocks; the co-batched request never saw the page at all
    assert victim.state == "done" and victim.attempts == 2
    assert other.state == "done" and other.attempts == 1
    np.testing.assert_array_equal(np.asarray(victim.tokens), bases[0])
    np.testing.assert_array_equal(np.asarray(other.tokens), bases[1])


def test_corrupted_page_without_integrity_stays_contained():
    """Same drill, integrity off on a *finished* request's recycled
    page: corruption of pool bytes can change at most the owner —
    here nobody, since the probe is gated on integrity mode. The
    engine must simply not probe (zero overhead discipline)."""
    mesh, params, sv, prompts, bases = _setup(integrity="none")
    eng = Engine(params, mesh, CFG, sv)
    rids = [eng.submit(p, 10) for p in prompts]
    plan = chaos.FaultPlan(schedule={"corrupt:serve.kv.page": (0,)})
    with chaos.inject(plan):
        eng.run()
    assert plan.fired("corrupt", "serve.kv.page") == 0
    for rid, base in zip(rids, bases):
        np.testing.assert_array_equal(
            np.asarray(eng.queue.request(rid).tokens), base)


def test_clean_armed_run_identical_to_unarmed():
    """A plan that never fires must leave the engine bit-identical to
    an unarmed run — the injection sites themselves are free."""
    mesh, params, sv, prompts, bases = _setup(integrity="pages")
    eng = Engine(params, mesh, CFG, sv)
    rids = [eng.submit(p, 10) for p in prompts]
    plan = chaos.FaultPlan(rates={"die:serve.*": 0.0})
    with chaos.inject(plan):
        eng.run()
    assert plan.log == []
    for rid, base in zip(rids, bases):
        req = eng.queue.request(rid)
        assert req.state == "done" and req.attempts == 1
        np.testing.assert_array_equal(np.asarray(req.tokens), base)


def test_admit_delay_site_fires_without_changing_output():
    mesh, params, sv, prompts, bases = _setup(n=1)
    eng = Engine(params, mesh, CFG, sv)
    rid = eng.submit(prompts[0], 10)
    plan = chaos.FaultPlan(rates={"delay:serve.admit": 1.0},
                           delay_s=0.001)
    with chaos.inject(plan):
        eng.run()
    assert plan.fired("delay", "serve.admit") >= 1
    np.testing.assert_array_equal(
        np.asarray(eng.queue.request(rid).tokens), bases[0])


def test_shared_prefix_block_sdc_fails_every_sharer_and_quarantines():
    """The r11 drill: SDC on a *shared* prefix block. Every request
    whose table maps the page must fail its sealed-page verify (the
    digest is content-keyed — one page, one digest, many readers),
    the page must leave the prefix index (no retry may re-attach the
    bad content), and the retries must re-prefill on fresh blocks —
    with the non-sharing co-batched request's output bitwise
    unchanged."""
    from icikit.serve.kvpool import block_hashes

    mesh, params, sv, prompts, bases = _setup(integrity="pages")
    rng = np.random.default_rng(21)
    shared_p = rng.integers(0, CFG.vocab, (8,)).astype(np.int32)
    shared_base = np.asarray(greedy_generate(
        params, jnp.asarray(shared_p)[None], mesh, CFG, 10))[0, 8:]
    sv = ServeConfig(**{**sv.__dict__, "max_rows": 4})
    eng = Engine(params, mesh, CFG, sv)
    # seed the cache: one clean pass over the shared prompt
    r_seed = eng.submit(shared_p, 10)
    eng.run()
    h0 = block_hashes(shared_p, sv.block_size)[0]
    page0 = eng.pool.allocators[0].indexed(h0)
    assert page0 is not None
    # two sharers + one bystander, admitted together
    r_b = eng.submit(shared_p, 10)
    r_c = eng.submit(shared_p, 10)
    r_d = eng.submit(prompts[1], 10)
    plan = chaos.FaultPlan(schedule={"corrupt:serve.kv.page": (0,)})
    with chaos.inject(plan):
        eng.run()
    assert plan.fired("corrupt", "serve.kv.page") == 1
    # every sharer failed once and retried to the correct answer
    for rid in (r_b, r_c):
        req = eng.queue.request(rid)
        assert req.state == "done" and req.attempts == 2
        np.testing.assert_array_equal(np.asarray(req.tokens),
                                      shared_base)
    # the bystander never noticed
    d = eng.queue.request(r_d)
    assert d.state == "done" and d.attempts == 1
    np.testing.assert_array_equal(np.asarray(d.tokens), bases[1])
    # the seed request's record is untouched
    assert eng.queue.request(r_seed).attempts == 1
    # the corrupted page was quarantined from the index: the chain
    # re-registered onto a FRESH page by the re-prefill
    assert eng.pool.allocators[0].indexed(h0) != page0


def test_prefix_cache_clean_armed_run_identical(monkeypatch=None):
    """A never-firing plan over prefix-cached traffic (hits, CoW
    forks, evictions all live) leaves outputs bit-identical to the
    unarmed baseline — the injection sites stay free under the new
    admission path too."""
    mesh, params, sv, prompts, bases = _setup(integrity="pages")
    rng = np.random.default_rng(22)
    p = rng.integers(0, CFG.vocab, (8,)).astype(np.int32)
    base = np.asarray(greedy_generate(
        params, jnp.asarray(p)[None], mesh, CFG, 10))[0, 8:]
    eng = Engine(params, mesh, CFG, sv)
    rids = [eng.submit(p, 10) for _ in range(3)]
    plan = chaos.FaultPlan(rates={"die:serve.*": 0.0,
                                  "delay:serve.prefill.chunk": 0.0})
    with chaos.inject(plan):
        eng.run()
    assert plan.log == []
    assert eng.prefix_stats()["hits"] >= 1
    for rid in rids:
        req = eng.queue.request(rid)
        assert req.state == "done" and req.attempts == 1
        np.testing.assert_array_equal(np.asarray(req.tokens), base)


def test_sampled_dead_engine_reissues_bitwise():
    """The r12 sampled extension of the dead-engine drill: a SAMPLED
    request abandoned by a dying engine is replayed by a second
    engine bitwise identically — the counter keys are a pure function
    of (seed, position), so reissue carries no engine state and the
    replay IS the original draw."""
    import jax.numpy as jnp

    from icikit.models.transformer.decode import sample_generate
    mesh, params, sv, prompts, _ = _setup()
    sample_bases = [np.asarray(sample_generate(
        params, jnp.asarray(p)[None], mesh, CFG, 10, jax.random.key(0),
        temperature=0.9, top_p=0.9, seeds=[40 + i]))[0, 8:]
        for i, p in enumerate(prompts)]
    q = RequestQueue(lease_s=0.05)
    eng1 = Engine(params, mesh, CFG, sv, queue=q)
    rids = [eng1.submit(p, 10, seed=40 + i, temperature=0.9,
                        top_p=0.9)
            for i, p in enumerate(prompts)]
    plan = chaos.FaultPlan(schedule={"die:serve.step": (0,)})
    with chaos.inject(plan):
        with pytest.raises(chaos.InjectedDeath):
            eng1.run()   # dies at the first step; leases dangle
        assert not q.drained() and len(q.done) == 0
        time.sleep(0.06)                     # outlive the leases
        eng2 = Engine(params, mesh, CFG, sv, queue=q)
        eng2.run()                           # reap -> reissue -> done
    assert q.n_reissues == len(rids)
    for rid, base in zip(rids, sample_bases):
        req = q.request(rid)
        assert req.state == "done" and req.attempts == 2
        np.testing.assert_array_equal(np.asarray(req.tokens), base)


def test_sampled_clean_armed_run_identical():
    """A never-firing plan over mixed greedy+sampled traffic leaves
    every output bit-identical to the unarmed baselines — the
    injection sites stay free on the sampled path too."""
    import jax.numpy as jnp

    from icikit.models.transformer.decode import sample_generate
    mesh, params, sv, prompts, bases = _setup(integrity="pages")
    want_s = np.asarray(sample_generate(
        params, jnp.asarray(prompts[1])[None], mesh, CFG, 10,
        jax.random.key(0), temperature=1.1, seeds=[9]))[0, 8:]
    eng = Engine(params, mesh, CFG, sv)
    r_g = eng.submit(prompts[0], 10)
    r_s = eng.submit(prompts[1], 10, seed=9, temperature=1.1)
    plan = chaos.FaultPlan(rates={"die:serve.*": 0.0})
    with chaos.inject(plan):
        eng.run()
    assert plan.log == []
    np.testing.assert_array_equal(
        np.asarray(eng.queue.request(r_g).tokens), bases[0])
    np.testing.assert_array_equal(
        np.asarray(eng.queue.request(r_s).tokens), want_s)


def test_slow_chunked_prefill_renews_its_lease():
    """A prompt whose chunked prefill outlasts lease_s must NOT be
    reaped mid-prefill: each chunk is a heartbeat (the step loop's
    renewal discipline extends to the prefill stream). Drill: delay
    every chunk past the lease and assert single-attempt completion
    with baseline tokens."""
    mesh, params, sv, prompts, bases = _setup(n=1)
    q = RequestQueue(lease_s=0.05)
    sv = ServeConfig(**{**sv.__dict__, "prefill_chunk": 4})
    eng = Engine(params, mesh, CFG, sv, queue=q)
    rid = eng.submit(prompts[0], 10)      # 8 tokens -> 2 chunks
    plan = chaos.FaultPlan(rates={"delay:serve.prefill.chunk": 1.0},
                           delay_s=0.06)  # each chunk outlives lease_s
    with chaos.inject(plan):
        eng.run()
    assert plan.fired("delay", "serve.prefill.chunk") >= 2
    req = q.request(rid)
    assert req.state == "done" and req.attempts == 1
    assert q.n_reissues == 0
    np.testing.assert_array_equal(np.asarray(req.tokens), bases[0])
