"""Bitwise reproducibility — the framework's answer to SURVEY.md §5.2.

The reference has no race detector; its de-facto one is the pattern
oracles. On TPU the equivalent hazard is nondeterministic accumulation
order; these tests pin the contract that identical inputs produce
bit-identical outputs across repeated executions and across program
rebuilds, which is also what makes the p-invariant RNG and
checkpoint-resume guarantees meaningful."""

import jax
import jax.numpy as jnp
import numpy as np

from icikit.utils.mesh import make_mesh, shard_along


def test_collectives_bitwise_deterministic(mesh8):
    from icikit.parallel import all_reduce, scan_reduce
    rng = np.random.default_rng(0)
    data = jnp.asarray(rng.standard_normal((8, 64)).astype(np.float32))
    x = shard_along(data, mesh8)
    a = np.asarray(all_reduce(x, mesh8, algorithm="ring"))
    b = np.asarray(all_reduce(x, mesh8, algorithm="ring"))
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(
        np.asarray(scan_reduce(x, mesh8)), np.asarray(scan_reduce(x, mesh8)))


def test_sort_bitwise_deterministic(mesh8):
    from icikit.models.sort import sort
    rng = np.random.default_rng(1)
    keys = jnp.asarray(rng.integers(-1000, 1000, 4096).astype(np.int32))
    for alg in ("bitonic", "sample"):
        a = np.asarray(sort(keys, mesh8, algorithm=alg))
        b = np.asarray(sort(keys, mesh8, algorithm=alg))
        np.testing.assert_array_equal(a, b)


def test_train_step_bitwise_deterministic():
    from icikit.models.transformer import (
        TransformerConfig, init_params, make_train_step)
    from icikit.models.transformer.model import make_model_mesh

    cfg = TransformerConfig(vocab=64, d_model=16, n_heads=2, d_head=8,
                            d_ff=32, n_layers=2, max_seq=16,
                            compute_dtype="float32")
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    tok = jnp.arange(2 * 16, dtype=jnp.int32).reshape(2, 16) % 64
    tgt = jnp.ones((2, 16), jnp.int32)

    def one_run():
        params = init_params(jax.random.key(0), cfg, mesh)
        optimizer, step = make_train_step(mesh, cfg)
        st = optimizer.init(params)
        for _ in range(3):
            params, st, loss = step(params, st, tok, tgt)
        return params, float(loss)

    p1, l1 = one_run()
    p2, l2 = one_run()
    assert l1 == l2
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_discovery_cli(capsys):
    from icikit.__main__ import main
    assert main([]) == 0
    out = capsys.readouterr().out
    assert "allgather" in out and "scan" in out and "sort" in out
    assert "bench.northstar" in out
