"""RoPE: rotation properties, cross-mesh training parity, and decode
consistency — the relative-position property is what guarantees the
ring (sp), pipeline, and KV-cache paths all agree."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from icikit.ops.rope import apply_rope
from icikit.models.transformer import (
    TransformerConfig,
    greedy_generate,
    init_params,
    loss_fn,
)
from icikit.models.transformer.model import make_model_mesh

ROPE_CFG = TransformerConfig(vocab=61, d_model=32, n_heads=4, d_head=8,
                             d_ff=64, n_layers=2, max_seq=32,
                             compute_dtype="float32",
                             pos_encoding="rope")


def test_rotation_properties():
    x = jax.random.normal(jax.random.key(0), (2, 6, 3, 8))
    # position 0 is the identity rotation
    np.testing.assert_allclose(
        apply_rope(x, jnp.zeros(6, jnp.int32)), x, atol=1e-6)
    # rotations preserve norms
    r = apply_rope(x, jnp.arange(6))
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(r), axis=-1),
        np.linalg.norm(np.asarray(x), axis=-1), rtol=1e-5)


def test_relative_position_property():
    # <rope(q, i), rope(k, j)> depends only on i - j
    q = jax.random.normal(jax.random.key(1), (1, 1, 1, 16))
    k = jax.random.normal(jax.random.key(2), (1, 1, 1, 16))

    def dot_at(i, j):
        qi = apply_rope(q, jnp.array([i]))
        kj = apply_rope(k, jnp.array([j]))
        return float(jnp.sum(qi * kj))

    assert dot_at(5, 3) == pytest.approx(dot_at(9, 7), rel=1e-5)
    assert dot_at(5, 3) != pytest.approx(dot_at(5, 4), rel=1e-3)


def test_no_pos_param():
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), ROPE_CFG, mesh)
    assert "pos" not in params


@pytest.mark.parametrize("dp,tp,sp", [(1, 4, 2), (2, 2, 2)])
def test_rope_training_cross_mesh_parity(dp, tp, sp):
    """Loss and gradients on a sharded mesh equal the 1-device program —
    rope applied per-shard with global indices must agree globally."""
    rng = np.random.default_rng(0)
    tok = rng.integers(0, ROPE_CFG.vocab, (4, 32)).astype(np.int32)
    tgt = rng.integers(0, ROPE_CFG.vocab, (4, 32)).astype(np.int32)

    def run(dp, tp, sp):
        mesh = make_model_mesh(dp=dp, tp=tp, sp=sp)
        params = init_params(jax.random.key(0), ROPE_CFG, mesh)
        sh = NamedSharding(mesh, P("dp", "sp"))
        loss, grads = loss_fn(params,
                              jax.device_put(jnp.asarray(tok), sh),
                              jax.device_put(jnp.asarray(tgt), sh),
                              mesh, ROPE_CFG)
        return float(loss), jax.device_get(grads)

    l1, g1 = run(1, 1, 1)
    lp, gp = run(dp, tp, sp)
    assert l1 == pytest.approx(lp, rel=2e-5)
    for k in g1:
        np.testing.assert_allclose(np.asarray(gp[k]), np.asarray(g1[k]),
                                   atol=5e-5, rtol=5e-4, err_msg=k)


def test_rope_decode_matches_reforward():
    """KV-cache decode with rotated cached keys == full re-forward."""
    from icikit.models.attention.dense import dense_attention
    from icikit.models.transformer.model import _rms_norm

    mesh = make_model_mesh(dp=1, tp=2, sp=1)
    params = init_params(jax.random.key(0), ROPE_CFG, mesh)
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, ROPE_CFG.vocab, (2, 6)).astype(np.int32)
    pd = jax.device_put(jnp.asarray(prompt),
                        NamedSharding(mesh, P("dp", None)))
    got = np.asarray(greedy_generate(params, pd, mesh, ROPE_CFG, n_new=5))

    p = {k: jnp.asarray(np.asarray(v)) for k, v in params.items()}
    toks = jnp.asarray(prompt)
    for _ in range(5):
        s = toks.shape[1]
        x = p["emb"][toks]
        for li in range(ROPE_CFG.n_layers):
            h = _rms_norm(x, p["ln1"][li])
            qkv = jnp.einsum("bsd,dthe->bsthe", h, p["wqkv"][li])
            q = apply_rope(qkv[:, :, 0], jnp.arange(s))
            k = apply_rope(qkv[:, :, 1], jnp.arange(s))
            attn = dense_attention(q, k, qkv[:, :, 2], causal=True)
            x = x + jnp.einsum("bshe,hed->bsd", attn, p["wo"][li])
            h2 = _rms_norm(x, p["ln2"][li])
            u = jax.nn.gelu(jnp.einsum("bsd,df->bsf", h2, p["w1"][li]))
            x = x + jnp.einsum("bsf,fd->bsd", u, p["w2"][li])
        x = _rms_norm(x, p["ln_f"])
        logits = jnp.einsum("bd,vd->bv", x[:, -1], p["w_out"])
        nxt = jnp.argmax(logits, axis=-1).astype(toks.dtype)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(got, np.asarray(toks))


def test_bad_pos_encoding_rejected():
    cfg = TransformerConfig(pos_encoding="alibi")
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    with pytest.raises(ValueError, match="pos_encoding"):
        init_params(jax.random.key(0), cfg, mesh)
    with pytest.raises(ValueError, match="even d_head"):
        init_params(jax.random.key(0),
                    TransformerConfig(d_head=7, pos_encoding="rope"), mesh)
