"""The examples/ scripts stay runnable — each is a subprocess on the
simulated CPU mesh (they are the library's public face; a rotted
example is worse than none)."""

import os
import subprocess
import sys
from pathlib import Path

import pytest

_REPO = Path(__file__).resolve().parents[1]


def _run(name: str, timeout: int = 600):
    env = dict(os.environ)
    keep = [x for x in env.get("PYTHONPATH", "").split(os.pathsep)
            if x and not os.path.exists(os.path.join(x, "sitecustomize.py"))]
    env["PYTHONPATH"] = os.pathsep.join([str(_REPO)] + keep)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    return subprocess.run(
        [sys.executable, str(_REPO / "examples" / name)],
        capture_output=True, text=True, timeout=timeout, cwd=_REPO,
        env=env)


@pytest.mark.slow
def test_collectives_study_example():
    proc = _run("collectives_study.py")
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "allgather" in proc.stdout


@pytest.mark.slow
def test_distributed_sort_example():
    proc = _run("distributed_sort.py")
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "check_sort errors: 0" in proc.stdout


@pytest.mark.slow
def test_load_balancing_example():
    proc = _run("load_balancing.py")
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "[dynamic]" in proc.stdout
