"""Trained early-exit draft head tests (models/transformer/draft.py).

The load-bearing invariants:

1. **Token identity is drafter-independent** — ``speculative_generate``
   with the trained head (any head state, trained or random) stays
   token-identical to baseline greedy across meshes, GQA, rope and the
   vocab-parallel head: committed tokens are always the verify pass's
   full-model argmax.
2. **The head is a pure add-on to training** — arming ``draft_head``
   leaves the trunk's gradients (and the trunk's init) bitwise
   unchanged: x_mid and the tied unembedding enter the distill loss
   under stop_gradient, so only ``draft_*`` leaves move from it.
3. **Zero-init equivalence** — the freshly initialized head (zero
   adapter, unit norm scale, tied table) IS the r7 shared-head
   drafter: identical draft tokens, identical acceptance.
4. Distillation learns (draft loss falls, top-1 agreement rises), and
   the optimizer param group (``draft_lr_mult``) really scopes to the
   ``draft_*`` leaves.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from icikit.models.transformer import (
    TransformerConfig,
    init_params,
    make_train_step,
    speculative_generate,
)
from icikit.models.transformer.decode import greedy_generate
from icikit.models.transformer.model import (
    loss_and_metrics,
    loss_fn,
    make_model_mesh,
    param_specs,
)

CFG = TransformerConfig(vocab=61, d_model=32, n_heads=4, d_head=8,
                        d_ff=64, n_layers=4, max_seq=48,
                        compute_dtype="float32",
                        draft_head=True, draft_layers=1, draft_rank=8)


def _prompt(mesh, b=3, s=8, vocab=61, seed=0):
    rng = np.random.default_rng(seed)
    return jax.device_put(
        jnp.asarray(rng.integers(0, vocab, (b, s)), jnp.int32),
        NamedSharding(mesh, P("dp", None)))


def _perturbed(params, scale=0.5, seed=3):
    """A *non-trivially wrong* draft head: random adapter B — drafts
    must now disagree with the shared head, and identity must hold
    anyway."""
    k = jax.random.key(seed)
    return {**params,
            "draft_b": scale * jax.random.normal(
                k, params["draft_b"].shape, jnp.float32)}


def test_param_branch_and_trunk_init_parity():
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    cfg0 = dataclasses.replace(CFG, draft_head=False)
    p0 = init_params(jax.random.key(0), cfg0, mesh)
    p1 = init_params(jax.random.key(0), CFG, mesh)
    assert {"draft_ln", "draft_a", "draft_b"} == set(p1) - set(p0)
    for k in p0:  # arming the head must not reshuffle the trunk init
        np.testing.assert_array_equal(np.asarray(p0[k]),
                                      np.asarray(p1[k]))
    assert p1["draft_a"].shape == (CFG.d_model, CFG.draft_rank)
    assert not np.any(np.asarray(p1["draft_b"]))   # zero adapter


def test_zero_init_head_is_the_shared_drafter():
    """Fresh head == r7 shared-head drafter: same drafts, same
    acceptance, token for token."""
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    pd = _prompt(mesh)
    out_t, st_t = speculative_generate(params, pd, mesh, CFG, 10, k=3,
                                       draft_layers=1,
                                       drafter="trained",
                                       return_stats=True)
    out_s, st_s = speculative_generate(params, pd, mesh, CFG, 10, k=3,
                                       draft_layers=1,
                                       drafter="shared",
                                       return_stats=True)
    np.testing.assert_array_equal(np.asarray(out_t), np.asarray(out_s))
    assert st_t["acceptance_rate"] == st_s["acceptance_rate"]
    assert st_t["drafter"] == "trained"


@pytest.mark.parametrize("k", [2, 4])
def test_trained_head_token_identity(k):
    """A deliberately WRONG head still yields baseline-greedy tokens —
    the accept loop only ever commits full-model argmaxes."""
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = _perturbed(init_params(jax.random.key(0), CFG, mesh))
    pd = _prompt(mesh)
    base = np.asarray(greedy_generate(params, pd, mesh, CFG, n_new=10))
    got = np.asarray(speculative_generate(params, pd, mesh, CFG, 10,
                                          k=k, drafter="trained"))
    np.testing.assert_array_equal(got, base)


@pytest.mark.parametrize("dp,tp", [(1, 4), (2, 2)])
@pytest.mark.parametrize("variant", ["dense", "rope", "vocab_parallel",
                                     "gqa", "untied"])
def test_trained_head_identity_sharded(dp, tp, variant):
    over = {"rope": {"pos_encoding": "rope"},
            "vocab_parallel": {"vocab_parallel": True},
            "gqa": {"n_kv_heads": 2},
            "untied": {"draft_tied": False},
            "dense": {}}[variant]
    if variant == "gqa" and 2 % tp:
        pytest.skip("kv heads must divide over tp")
    cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4, d_head=8,
                            d_ff=64, n_layers=3, max_seq=32,
                            compute_dtype="float32",
                            draft_head=True, draft_layers=1,
                            draft_rank=4, **over)
    mesh = make_model_mesh(dp=dp, tp=tp, sp=1)
    params = _perturbed(init_params(jax.random.key(0), cfg, mesh))
    pd = _prompt(mesh, b=4, s=6, vocab=64, seed=1)
    base = np.asarray(greedy_generate(params, pd, mesh, cfg, n_new=8))
    got = np.asarray(speculative_generate(params, pd, mesh, cfg, 8,
                                          k=3, drafter="trained"))
    np.testing.assert_array_equal(got, base)


def test_auto_drafter_resolution_and_validation():
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    pd = _prompt(mesh)
    # auto on a draft cfg -> trained (reported in stats)
    _, st = speculative_generate(params, pd, mesh, CFG, 6, k=2,
                                 return_stats=True)
    assert st["drafter"] == "trained"
    # default draft_layers under trained = the configured exit depth
    cfg0 = dataclasses.replace(CFG, draft_head=False)
    p0 = init_params(jax.random.key(0), cfg0, mesh)
    _, st0 = speculative_generate(p0, pd, mesh, cfg0, 6, k=2,
                                  return_stats=True)
    # the r11 flip: no trained head -> the zero-cost ngram matcher
    # (measured above the shared drafter on the r10 real-text stream)
    assert st0["drafter"] == "ngram"
    with pytest.raises(ValueError, match="drafter"):
        speculative_generate(p0, pd, mesh, cfg0, 6, k=2,
                             drafter="bogus")
    with pytest.raises(ValueError, match="draft_head"):
        speculative_generate(p0, pd, mesh, cfg0, 6, k=2,
                             drafter="trained")
    with pytest.raises(ValueError, match="draft_"):
        # draft cfg but params missing the branch
        speculative_generate(p0, pd, mesh, CFG, 6, k=2,
                             drafter="trained")


def test_distill_is_invisible_to_trunk_gradients():
    """The satellite pin for "stop-gradient through the trunk": with
    the head armed, every trunk leaf's gradient is BITWISE the
    no-draft gradient (loss differs — the draft term rides on top —
    but only draft_* leaves feel it)."""
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    cfg0 = dataclasses.replace(CFG, draft_head=False)
    p0 = init_params(jax.random.key(0), cfg0, mesh)
    p1 = init_params(jax.random.key(0), CFG, mesh)
    rng = np.random.default_rng(0)
    tok = jnp.asarray(rng.integers(0, 61, (4, 16)), jnp.int32)
    tgt = jnp.asarray(rng.integers(0, 61, (4, 16)), jnp.int32)
    l0, g0 = loss_fn(p0, tok, tgt, mesh, cfg0)
    l1, g1, m1 = loss_and_metrics(p1, tok, tgt, mesh, CFG)
    assert float(l1) > float(l0)      # the draft CE+KL term is in there
    for k in g0:
        np.testing.assert_array_equal(np.asarray(g0[k]),
                                      np.asarray(g1[k]))
    for k in ("draft_ln", "draft_a", "draft_b"):
        assert k in g1
    assert set(m1) == {"draft_loss", "draft_top1_agree"}
    assert np.isfinite(float(m1["draft_loss"]))


def test_distillation_learns():
    """A few dozen steps on a fixed batch: draft loss drops, top-1
    agreement with the teacher rises far above the untrained start."""
    import optax
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    _, step = make_train_step(mesh, CFG, optax.adam(1e-2))
    st = optax.adam(1e-2).init(params)
    rng = np.random.default_rng(0)
    tok = jnp.asarray(rng.integers(0, 61, (4, 16)), jnp.int32)
    tgt = jnp.asarray(rng.integers(0, 61, (4, 16)), jnp.int32)
    first = None
    for i in range(30):
        params, st, loss, metrics = step(params, st, tok, tgt)
        if first is None:
            first = {k: float(v) for k, v in metrics.items()}
    last = {k: float(v) for k, v in metrics.items()}
    assert last["draft_loss"] < first["draft_loss"] * 0.7
    assert last["draft_top1_agree"] > first["draft_top1_agree"] + 0.2


def test_draft_lr_mult_scopes_to_the_head():
    """draft_lr_mult=0 freezes exactly the draft branch: trunk leaves
    move, draft leaves hold bitwise."""
    from icikit.models.transformer.optim import make_optimizer
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    tx = make_optimizer(1e-2, draft_lr_mult=0.0)
    _, step = make_train_step(mesh, CFG, tx)
    st = tx.init(params)
    rng = np.random.default_rng(0)
    tok = jnp.asarray(rng.integers(0, 61, (4, 16)), jnp.int32)
    tgt = jnp.asarray(rng.integers(0, 61, (4, 16)), jnp.int32)
    new, _, _, _ = step(params, st, tok, tgt)
    for k in params:
        if k.startswith("draft_"):
            np.testing.assert_array_equal(
                np.asarray(new[k]), np.asarray(params[k]),
                err_msg=f"{k} moved under draft_lr_mult=0")
    for k in ("w1", "w2", "wqkv", "emb"):
        assert not np.array_equal(np.asarray(new[k]),
                                  np.asarray(params[k]))


def test_vocab_parallel_distill_matches_replicated():
    """The distributed CE/KL/argmax reductions under the Megatron head
    reproduce the replicated-head draft metrics (same params, same
    batch, tp=4 vs tp=1)."""
    cfg_r = TransformerConfig(vocab=64, d_model=32, n_heads=4, d_head=8,
                              d_ff=64, n_layers=2, max_seq=16,
                              compute_dtype="float32",
                              draft_head=True, draft_layers=1,
                              draft_rank=4)
    cfg_v = dataclasses.replace(cfg_r, vocab_parallel=True)
    mesh1 = make_model_mesh(dp=1, tp=1, sp=1)
    mesh4 = make_model_mesh(dp=1, tp=4, sp=1)
    params1 = _perturbed(init_params(jax.random.key(0), cfg_r, mesh1))
    specs_v = param_specs(cfg_v)
    params4 = {k: jax.device_put(np.asarray(v),
                                 NamedSharding(mesh4, specs_v[k]))
               for k, v in params1.items()}
    rng = np.random.default_rng(1)
    tok = jnp.asarray(rng.integers(0, 64, (2, 16)), jnp.int32)
    tgt = jnp.asarray(rng.integers(0, 64, (2, 16)), jnp.int32)
    l1, _, m1 = loss_and_metrics(params1, tok, tgt, mesh1, cfg_r)
    l4, _, m4 = loss_and_metrics(params4, tok, tgt, mesh4, cfg_v)
    assert float(m1["draft_top1_agree"]) == pytest.approx(
        float(m4["draft_top1_agree"]), abs=1e-6)
    assert float(m1["draft_loss"]) == pytest.approx(
        float(m4["draft_loss"]), rel=2e-5)


def test_config_validation():
    # validation fires at param_specs (_check_cfg), like every other
    # config knob
    with pytest.raises(ValueError, match="draft_layers"):
        param_specs(TransformerConfig(n_layers=2, draft_head=True,
                                      draft_layers=5))
    with pytest.raises(ValueError, match="draft_rank"):
        param_specs(TransformerConfig(draft_head=True, draft_rank=0))
    with pytest.raises(ValueError, match="draft_kl"):
        param_specs(TransformerConfig(draft_head=True, draft_kl=1.5))
    with pytest.raises(ValueError, match="save_stack"):
        param_specs(TransformerConfig(draft_head=True,
                                      save_stack="pallas"))
