"""Fleet telemetry plane (`icikit.fleet.telemetry` +
`icikit.obs.aggregate`): the engine-side forwarder and the chaos
drills on the channel itself.

The load-bearing claims:

- the forwarder's queue is BOUNDED and every loss mode (overflow,
  serialization failure, transport failure, injected death) drops and
  counts — a slow or dead collector can never stall the producer;
- batch content integrity is the telemetry layer's own: the digest is
  computed before the ``fleet.telemetry.send`` corruption probe, so a
  flipped frame passes the transport checksum and is caught by the
  collector's re-verify — dropped, counted, never parsed;
- ALL channel drills (corrupt send, corrupt recv, dead channel) leave
  committed tokens bitwise identical to the single-request decode,
  and the loss shows up in the collector's health verdict;
- the heartbeat's resident-chain bloom summary reaches the
  coordinator's roster state (false positives only — never a false
  negative, the polarity cache-aware routing needs).
"""

import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from icikit import chaos
from icikit.fleet import Coordinator, EngineWorker, RpcClient
from icikit.fleet.telemetry import (TelemetryForwarder, bloom_contains,
                                    bloom_hits, bloom_prefix_hits,
                                    chain_bloom, payload_digest)
from icikit.fleet.worker import build_model
from icikit.models.transformer import greedy_generate
from icikit.obs.aggregate import FleetCollector
from icikit.serve.engine import ServeConfig

MODEL_SPEC = {
    "preset": "tiny",
    "overrides": {"vocab": 64, "d_model": 32, "n_heads": 2,
                  "d_head": 16, "d_ff": 64, "n_layers": 2,
                  "max_seq": 64},
    "compute_dtype": "float32", "dp": 1, "tp": 1, "init_seed": 0,
}

SERVE_KW = dict(max_rows=2, block_size=4, n_blocks=32,
                max_prompt=20, max_new=12, prefill_chunk=8)


@pytest.fixture(scope="module")
def fleet_model():
    return build_model(MODEL_SPEC)


def _prompts(n, vocab, s=10, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, (s,)).astype(np.int32)
            for _ in range(n)]


def _run_workers(workers, timeout=180):
    threads = [threading.Thread(target=w.run, daemon=True)
               for w in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
    assert not any(t.is_alive() for t in threads), \
        "fleet run did not drain in time"


def _audit(coord, rids, prompts, n_new, model):
    """Every completed request bitwise vs its single-request decode."""
    params, mesh, cfg = model
    batch = jnp.asarray(np.stack(prompts))
    out = np.asarray(greedy_generate(params, batch, mesh, cfg, n_new))
    for rid, p, row in zip(rids, prompts, out):
        req = coord.queue.request(rid)
        assert req.state == "done", (rid, req.state, req.error)
        exp = [int(t) for t in row[len(p):len(p) + n_new]]
        got = [int(t) for t in req.tokens]
        assert got == exp and len(got) == n_new, (rid, got, exp)


# -- resident-chain bloom summaries ---------------------------------

def test_chain_bloom_no_false_negatives():
    chains = [f"chain-{i:04d}" for i in range(64)]
    s = chain_bloom(chains)
    assert s["n"] == 64 and s["bits"] == 1024 and s["k"] == 4
    # every inserted hash answers "maybe resident" — a false negative
    # would make cache-aware routing skip real KV
    assert all(bloom_contains(s, h) for h in chains)


def test_chain_bloom_mostly_rejects_absent_hashes():
    s = chain_bloom([f"chain-{i}" for i in range(16)])
    # false positives are allowed but must be rare at this load
    # (16 keys in 1024 bits); absent probes overwhelmingly miss
    misses = sum(not bloom_contains(s, f"other-{i}")
                 for i in range(200))
    assert misses >= 190, misses


def test_bloom_hits_counts_resident_prefix_only():
    chains = [f"c{i}" for i in range(8)]
    s = chain_bloom(chains[:5])
    # chain hashes are prefix-lineage keys: only the unbroken resident
    # prefix is reusable KV, so a mid-chain miss ends the count
    assert bloom_hits(s, chains) == 5
    assert bloom_hits(s, ["absent"] + chains[:5]) == 0
    assert bloom_hits(chain_bloom([]), chains) == 0


def test_chain_bloom_rejects_oversized_k():
    with pytest.raises(ValueError):
        chain_bloom(["x"], k=16)


# -- bloom_prefix_hits: the r20 routing score -----------------------

def test_bloom_prefix_hits_no_false_negatives():
    # a truly resident chain always scores its full depth against the
    # summary that advertised it — bloom polarity can inflate a
    # score (collision), never deflate it, so routing can never skip
    # real KV
    chains = [f"lineage-{i:03d}" for i in range(32)]
    s = chain_bloom(chains)
    assert bloom_prefix_hits(s, chains) == 32
    for cut in (1, 7, 31):
        assert bloom_prefix_hits(s, chains[:cut]) == cut


def test_bloom_prefix_hits_counts_unbroken_prefix_only():
    chains = [f"c{i}" for i in range(8)]
    s = chain_bloom(chains[:4])
    # chain hash h_j only pays off if h_0..h_{j-1} are resident too:
    # a deep unbroken prefix scores, scattered membership does not
    assert bloom_prefix_hits(s, chains) >= 4
    assert not bloom_contains(s, "absent-head")
    assert bloom_prefix_hits(s, ["absent-head"] + chains[:4]) == 0


def test_bloom_prefix_hits_false_positive_only_inflates():
    # worst-case false positive — a saturated summary claims
    # everything resident: the score inflates to the whole chain,
    # which mis-routes to a migration (a path every request may take
    # anyway), never to wrong tokens
    sat = {"bloom": "ff" * 128, "bits": 1024, "k": 4, "n": 1}
    chains = [f"x{i}" for i in range(6)]
    assert bloom_prefix_hits(sat, chains) == 6


def test_bloom_prefix_hits_malformed_summary_scores_cold():
    """The claim-path hardening: any summary a corrupt heartbeat (or
    an engine that never reported) could present scores 0 — the
    engine looks cold and routing degrades to blind dispatch, never
    to an exception inside the queue lock."""
    chains = ["a", "b"]
    good = chain_bloom(chains)
    assert bloom_prefix_hits(good, chains) == 2
    for bad in (None, {},
                {"bloom": "zz", "bits": 1024, "k": 4},   # not hex
                {"bloom": "00", "bits": 1024, "k": 4},   # truncated
                {"bloom": good["bloom"], "bits": 0, "k": 4},
                {"bloom": good["bloom"], "bits": 1024, "k": 0},
                {"bits": 1024, "k": 4},                  # no bloom
                {"bloom": 7, "bits": 1024, "k": 4},      # wrong type
                {"bloom": good["bloom"], "bits": "x", "k": 4}):
        assert bloom_prefix_hits(bad, chains) == 0, bad
    assert bloom_prefix_hits(good, []) == 0


# -- forwarder unit behavior (no sockets, no jax) -------------------

class _NullClient:
    """Never reachable — every call fails like a dead collector."""

    def call(self, op, msg, blobs=()):
        raise ConnectionError("collector down")

    def close(self):
        pass


class _CollectorClient:
    """Routes RPCs straight into a FleetCollector (loopback minus the
    socket — the payload bytes and digests are the real thing)."""

    def __init__(self, collector):
        self.collector = collector

    def call(self, op, msg, blobs=()):
        return self.collector.handle(op, msg, blobs)

    def close(self):
        pass


def test_bounded_queue_overflow_drops_and_counts():
    f = TelemetryForwarder(client=_NullClient(), source="e0",
                           queue_cap=4)
    for i in range(7):
        f.enqueue({"event": "x", "i": i})
    assert f.dropped == 3
    st = f.stats()
    assert st["source"] == "e0" and st["dropped"] == 3
    assert st["sent_batches"] == 0 and st["offset_us"] is None
    assert st["alive"] is False      # never started


def test_failed_send_drops_counts_and_forces_rehandshake():
    f = TelemetryForwarder(client=_NullClient(), source="e0")
    f.offset_us = 123                # pretend a handshake succeeded
    f.enqueue({"event": "x"})
    f._flush_once()
    # the batch is gone (drop, count) and the stale clock offset is
    # cleared: the next reachable collector may be a failed-over
    # standby in a fresh clock domain
    assert f.dropped >= 1
    assert f.offset_us is None


def test_hostile_event_payload_ships_sanitized_never_wedges():
    # a non-JSON event value (a set) rides the bus sink's strict-JSON
    # slow path: stringified, shipped, digest-verified — the channel
    # neither wedges nor drops over one hostile payload
    col = FleetCollector()
    f = TelemetryForwarder(client=_CollectorClient(col), source="e0")
    f.enqueue({"event": "bad", "payload": {1, 2, 3}})   # not JSON
    f._flush_once()
    assert f.dropped == 0
    st = col.stats()
    assert st["batches"] == 1 and st["corrupt_frames"] == 0
    assert st["sources"]["e0"]["events"] == 1


def test_flusher_ships_digest_verified_batches():
    col = FleetCollector()
    f = TelemetryForwarder(client=_CollectorClient(col), source="e0",
                           role="engine", flush_s=0.01)
    f.start(install_sink=False)
    try:
        f.enqueue({"event": "drill", "n": 1})
        deadline = time.monotonic() + 5.0
        while col.stats()["batches"] < 1:
            assert time.monotonic() < deadline, col.stats()
            time.sleep(0.005)
    finally:
        f.stop()
    assert f.offset_us is not None   # handshake completed
    st = col.stats()
    src = st["sources"]["e0"]
    assert src["events"] >= 1 and src["corrupt_frames"] == 0
    assert src["offset_us"] == f.offset_us
    assert col.verdict()["telemetry_loss"] == []


def test_send_corrupt_drill_caught_by_collector_reverify():
    """The content-rot drill end to end over the real payload path:
    the digest rides inside the RPC, the probe flips the payload after
    the digest is computed, the collector's re-verify refuses the
    batch without parsing it."""
    col = FleetCollector()
    f = TelemetryForwarder(client=_CollectorClient(col), source="e0")
    f._hello()
    plan = chaos.FaultPlan(
        schedule={"corrupt:fleet.telemetry.send": (0,)}, seed=5)
    with chaos.inject(plan):
        f.enqueue({"event": "drill", "n": 1})
        f._flush_once()              # batch 1: rotten in flight
        f.enqueue({"event": "drill", "n": 2})
        f._flush_once()              # batch 2: clean
    assert plan.fired("corrupt", "fleet.telemetry.send") == 1
    st = col.stats()
    assert st["corrupt_frames"] == 1
    assert st["sources"]["e0"]["events"] == 1     # only batch 2 parsed
    v = col.verdict()
    assert v["healthy"] is False
    assert {"source": "e0", "kind": "corrupt_frames", "n": 1} \
        in v["telemetry_loss"]


def test_recv_corrupt_drill_caught_before_parse():
    col = FleetCollector()
    f = TelemetryForwarder(client=_CollectorClient(col), source="e0")
    plan = chaos.FaultPlan(
        schedule={"corrupt:fleet.telemetry.recv": (0,)}, seed=6)
    with chaos.inject(plan):
        f.enqueue({"event": "drill"})
        f._flush_once()
    assert plan.fired("corrupt", "fleet.telemetry.recv") == 1
    assert col.stats()["corrupt_frames"] == 1
    assert col.verdict()["healthy"] is False


def test_digest_is_independent_of_transport_checksum():
    # the layer's own detector: same payload -> same digest, one
    # flipped byte -> different digest (what the collector re-verifies)
    p = b'{"events": [], "trace": [], "metrics": null}'
    d = payload_digest(p)
    assert d == payload_digest(bytes(p))
    assert d != payload_digest(p[:-1] + b"?")


# -- chaos drills against a live fleet (the bitwise pins) -----------

def _fleet(coord, fleet_model, n_workers=2):
    params, mesh, cfg = fleet_model
    sv = ServeConfig(**SERVE_KW)
    return [EngineWorker(coord.addr, f"e{i}", "both", params, mesh,
                         cfg, sv, report_interval_s=0.05)
            for i in range(n_workers)]


def test_corrupt_telemetry_frame_leaves_tokens_bitwise(
        fleet_model, tmp_path):
    """A flipped telemetry frame is a counted drop at the collector —
    and NOTHING else: the engines' committed tokens stay bitwise the
    single-request decode (the telemetry plane observes the data
    plane, it must never perturb it)."""
    _, _, cfg = fleet_model
    col = FleetCollector()
    coord = Coordinator(tmp_path / "bridge", lease_s=10.0,
                        collector=col)
    tele = TelemetryForwarder(coord.addr, source="tele0",
                              role="engine", flush_s=0.02)
    try:
        workers = _fleet(coord, fleet_model)
        prompts = _prompts(3, cfg.vocab, seed=4)
        rids = [coord.submit(p, 6) for p in prompts]
        plan = chaos.FaultPlan(
            schedule={"corrupt:fleet.telemetry.send": (0,)}, seed=7)
        with chaos.inject(plan):
            tele.start()
            tele.enqueue({"event": "drill"})
            deadline = time.monotonic() + 10.0
            while col.stats()["corrupt_frames"] < 1:
                assert time.monotonic() < deadline, col.stats()
                time.sleep(0.01)
            _run_workers(workers)
        assert plan.fired("corrupt", "fleet.telemetry.send") == 1
        _audit(coord, rids, prompts, 6, fleet_model)
        v = col.verdict()
        assert v["healthy"] is False
        assert any(loss["source"] == "tele0"
                   and loss["kind"] == "corrupt_frames"
                   for loss in v["telemetry_loss"]), v
        for w in workers:
            w.close()
    finally:
        tele.stop()
        coord.shutdown()


def test_dead_channel_drops_count_generation_unperturbed(
        fleet_model, tmp_path):
    """The dead-channel drill: ``die:fleet.telemetry.send`` kills the
    flusher THREAD, not the engine — the channel goes quiet, drops
    count from then on, and every committed token is bitwise the
    single-request decode."""
    _, _, cfg = fleet_model
    col = FleetCollector()
    coord = Coordinator(tmp_path / "bridge", lease_s=10.0,
                        collector=col)
    tele = TelemetryForwarder(coord.addr, source="tele0",
                              role="engine", flush_s=0.02)
    try:
        workers = _fleet(coord, fleet_model)
        prompts = _prompts(3, cfg.vocab, seed=5)
        rids = [coord.submit(p, 6) for p in prompts]
        plan = chaos.FaultPlan(
            schedule={"die:fleet.telemetry.send": (0,)}, seed=8)
        with chaos.inject(plan):
            tele.start()
            tele.enqueue({"event": "drill"})
            deadline = time.monotonic() + 10.0
            while tele.alive():
                assert time.monotonic() < deadline
                time.sleep(0.01)
            _run_workers(workers)
        assert plan.fired("die", "fleet.telemetry.send") == 1
        assert tele.alive() is False
        assert tele.dropped >= 1         # the dying batch is counted
        assert tele.stats()["sent_batches"] == 0
        # the producer side never blocks on the dead channel
        tele.enqueue({"event": "after-death"})
        _audit(coord, rids, prompts, 6, fleet_model)
        for w in workers:
            w.close()
    finally:
        tele.stop()
        coord.shutdown()


# -- heartbeat bloom -> coordinator roster state --------------------

def test_heartbeat_bloom_reaches_coordinator_roster(
        fleet_model, tmp_path):
    """Engines summarize their resident KV chains into every
    heartbeat; the collector keeps the per-engine roster state and the
    coordinator serves it via the ``resident_chains`` op — the
    substrate for cache-aware claim routing."""
    _, _, cfg = fleet_model
    col = FleetCollector()
    coord = Coordinator(tmp_path / "bridge", lease_s=10.0,
                        collector=col)
    try:
        workers = _fleet(coord, fleet_model)
        prompts = _prompts(4, cfg.vocab, seed=6)
        rids = [coord.submit(p, 6) for p in prompts]
        _run_workers(workers)
        _audit(coord, rids, prompts, 6, fleet_model)
        summaries = col.resident_summaries()
        assert set(summaries) == {"e0", "e1"}, summaries
        # at least one engine served, so its summary saw real chains
        assert any(s["n"] >= 1 for s in summaries.values()), summaries
        # the roster answers over RPC too
        cli = RpcClient(coord.addr)
        reply, _ = cli.call("resident_chains", {})
        cli.close()
        assert reply["resident"] == summaries
        # no false negatives: whatever is STILL resident on an engine
        # that its last heartbeat also saw must answer "maybe"
        for w in workers:
            s = summaries[w.engine_id]
            if s["n"]:
                chains = w.engine.resident_chains()
                assert bloom_hits(s, chains) >= 0   # prefix-counting
                hits = sum(bloom_contains(s, h) for h in chains)
                assert hits >= min(len(chains), 1) or not chains
        for w in workers:
            w.close()
    finally:
        coord.shutdown()


def test_unarmed_coordinator_refuses_telemetry_ops(tmp_path):
    coord = Coordinator(tmp_path / "bridge", lease_s=10.0)
    try:
        cli = RpcClient(coord.addr, retries=1)
        with pytest.raises(Exception, match="not armed"):
            cli.call("telemetry.hello", {"source": "x", "role": "e",
                                         "pid": 1})
        # the roster query degrades to empty, not an error
        reply, _ = cli.call("resident_chains", {})
        assert reply["resident"] == {}
        cli.close()
    finally:
        coord.shutdown()


def test_forwarder_thread_name_and_clean_stop():
    col = FleetCollector()
    f = TelemetryForwarder(client=_CollectorClient(col), source="eX",
                           flush_s=0.01)
    f.start(install_sink=False)
    try:
        assert f.alive()
        names = [t.name for t in threading.enumerate()]
        assert "fleet-telemetry-eX" in names
    finally:
        f.stop()
    assert not f.alive()


