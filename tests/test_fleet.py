"""icikit.fleet — coordinator, roles, migration, defect scheduling.

The cross-process composition claims under test (in-process workers
over REAL sockets — the transport serializes everything, so these pins
cover the wire contract; the subprocess soak lives in
tests/test_fleet_soak.py):

- multi-engine serving is bitwise single-request generate /
  sample_generate per request (counter keys carry no engine state);
- prefill/decode disaggregation hands off through the block bridge:
  the decode engine MIGRATES the prefill engine's sealed blocks
  (digest-verified at swap-in) instead of recomputing them, and the
  spliced token stream is bitwise the unsplit one;
- claim-seq fencing across processes: a stalled engine whose request
  was reaped cannot complete it via RPC;
- a flipped bridged byte is quarantined bridge-wide and recomputed
  fresh (no retry burned), co-batched rows bitwise unchanged;
- an engine whose completions fail KV integrity verify is quarantined
  (no further claims) and its in-flight work reissues bitwise;
- a restarted coordinator re-serves the persisted bridge and a fresh
  engine re-warms from it;
- one request stays ONE trace tree across a cross-engine reissue.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from icikit import chaos, obs
from icikit.fleet import Coordinator, EngineWorker, RpcClient
from icikit.fleet import worker as fleet_worker
from icikit.fleet.kvbridge import BlockBridge, encode_arrays
from icikit.fleet.telemetry import chain_bloom
from icikit.fleet.worker import build_model
from icikit.models.transformer import greedy_generate
from icikit.models.transformer.decode import sample_generate
from icikit.obs import trace_ctx
from icikit.serve.engine import ServeConfig
from icikit.serve.kvpool import block_hashes
from icikit.serve.scheduler import RequestQueue, prompt_checksum
from icikit.serve.store import PrefixStore

MODEL_SPEC = {
    "preset": "tiny",
    "overrides": {"vocab": 64, "d_model": 32, "n_heads": 2,
                  "d_head": 16, "d_ff": 64, "n_layers": 2,
                  "max_seq": 64},
    "compute_dtype": "float32", "dp": 1, "tp": 1, "init_seed": 0,
}

SERVE_KW = dict(max_rows=2, block_size=4, n_blocks=32,
                max_prompt=20, max_new=12, prefill_chunk=8)


@pytest.fixture(scope="module")
def fleet_model():
    return build_model(MODEL_SPEC)


def _prompts(n, vocab, s=10, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, (s,)).astype(np.int32)
            for _ in range(n)]


def _run_workers(workers, timeout=180):
    threads = [threading.Thread(target=w.run, daemon=True)
               for w in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
    assert not any(t.is_alive() for t in threads), \
        "fleet run did not drain in time"


def _audit(coord, rids, prompts, n_new, model, temperature=0.0,
           top_p=1.0, seeds=None):
    """Every completed request bitwise vs its single-request decode."""
    params, mesh, cfg = model
    batch = jnp.asarray(np.stack(prompts))
    if temperature > 0.0:
        out = np.asarray(sample_generate(
            params, batch, mesh, cfg, n_new, jax.random.key(0),
            temperature=temperature, top_p=top_p,
            seeds=np.asarray(seeds, np.int32)))
    else:
        out = np.asarray(greedy_generate(
            params, batch, mesh, cfg, n_new))
    for rid, p, row in zip(rids, prompts, out):
        req = coord.queue.request(rid)
        assert req.state == "done", (rid, req.state, req.error)
        exp = [int(t) for t in row[len(p):len(p) + n_new]]
        got = [int(t) for t in req.tokens]
        assert got == exp[:len(got)] and len(got) == n_new, \
            (rid, got, exp)


def test_two_engines_share_one_queue_bitwise(fleet_model, tmp_path):
    params, mesh, cfg = fleet_model
    coord = Coordinator(tmp_path / "bridge", lease_s=10.0)
    try:
        sv = ServeConfig(**SERVE_KW)
        workers = [EngineWorker(coord.addr, f"e{i}", "both",
                                params, mesh, cfg, sv)
                   for i in range(2)]
        prompts = _prompts(5, cfg.vocab)
        rids = [coord.submit(p, 6) for p in prompts]
        _run_workers(workers)
        _audit(coord, rids, prompts, 6, fleet_model)
        # both engines really served (the queue is shared)
        assert sum(len(w.queue.done) for w in workers) == 5
        for w in workers:
            w.close()
    finally:
        coord.shutdown()


def test_disaggregation_migrates_kv_and_stays_bitwise(
        fleet_model, tmp_path):
    """The DistServe split: prefill engine computes the prompt + first
    token, streams sealed blocks to the bridge; the decode engine
    pulls them (cross-engine migration), re-verifies each content
    digest, and continues — greedy AND sampled streams bitwise the
    unsplit single-request decode."""
    params, mesh, cfg = fleet_model
    coord = Coordinator(tmp_path / "bridge", lease_s=10.0)
    try:
        sv = ServeConfig(**SERVE_KW)
        pre = EngineWorker(coord.addr, "pre0", "prefill",
                           params, mesh, cfg, sv)
        dec = EngineWorker(coord.addr, "dec0", "decode",
                           params, mesh, cfg, sv)
        prompts = _prompts(4, cfg.vocab, seed=1)
        rids = [coord.submit(p, 6) for p in prompts[:2]]
        srids = [coord.submit(p, 6, seed=i, temperature=0.7,
                              top_p=0.9)
                 for i, p in enumerate(prompts[2:])]
        _run_workers([pre, dec])
        _audit(coord, rids, prompts[:2], 6, fleet_model)
        _audit(coord, srids, prompts[2:], 6, fleet_model,
               temperature=0.7, top_p=0.9, seeds=[0, 1])
        assert coord.n_handoffs == 4
        stats = coord.bridge.stats()
        assert stats["migrations"] > 0, stats
        # the decode engine restored the bridged chain instead of
        # recomputing the prompt: its computed prefill positions are
        # the one spliced token per request, not the whole prompt
        dstats = dec.engine.prefix_stats()
        assert dstats["restores"] > 0
        # per request: 2 full 4-token blocks of the 10-token prompt
        # migrate; the tail (2 positions + the spliced first token)
        # recomputes — 3 positions, not 11
        assert dstats["prefill_tokens"] <= 3 * len(prompts)
        pre.close(); dec.close()
    finally:
        coord.shutdown()


def test_claim_seq_fencing_across_processes(tmp_path):
    """A stalled engine whose request was reaped cannot complete it
    via RPC: the late commit is a counted no-op and the reissued
    claim's tokens stand."""
    coord = Coordinator(tmp_path / "bridge", lease_s=0.2,
                        reap_interval_s=0.05)
    try:
        cli = RpcClient(coord.addr)
        cli.call("hello", {"engine": "stale", "role": "both"})
        cli.call("hello", {"engine": "live", "role": "both"})
        rid = coord.submit(np.arange(4, dtype=np.int32), 3)
        reply, _ = cli.call("claim", {"engine": "stale"})
        w = reply["req"]
        assert w["rid"] == rid and w["claim_seq"] == 1
        # the stale engine stops renewing; the reaper reissues
        deadline = time.monotonic() + 5.0
        while coord.queue.request(rid).state != "queued":
            assert time.monotonic() < deadline
            time.sleep(0.02)
        reply, _ = cli.call("claim", {"engine": "live"})
        w2 = reply["req"]
        assert w2["rid"] == rid and w2["claim_seq"] == 2
        # late commit under the reaped generation: fenced, counted
        reply, _ = cli.call("complete", {
            "engine": "stale", "rid": rid, "seq": 1,
            "tokens": [9, 9, 9], "marks": {}})
        assert reply["committed"] is False
        assert coord.queue.n_duplicate_commits >= 1
        # the live claimant's commit stands
        reply, _ = cli.call("complete", {
            "engine": "live", "rid": rid, "seq": 2,
            "tokens": [1, 2, 3], "marks": {}})
        assert reply["committed"] is True
        assert [int(t) for t in coord.queue.request(rid).tokens] \
            == [1, 2, 3]
        assert coord.queue.n_reissues >= 1
        cli.close()
    finally:
        coord.shutdown()


def test_bridged_byte_flip_quarantined_and_recomputed(
        fleet_model, tmp_path):
    """The seal-verify-on-migrate drill: one bridged block's bytes rot
    between the coordinator's disk and the decode engine's arena
    (past the wire checksums — ``fleet.kv.pull``). The swap-in digest
    catches it, the content is quarantined from EVERY tier (the
    bridge file is removed), the row recomputes fresh without burning
    a retry, and co-batched rows are bitwise unchanged."""
    params, mesh, cfg = fleet_model
    coord = Coordinator(tmp_path / "bridge", lease_s=10.0)
    try:
        sv = ServeConfig(**SERVE_KW)
        pre = EngineWorker(coord.addr, "pre0", "prefill",
                           params, mesh, cfg, sv)
        dec = EngineWorker(coord.addr, "dec0", "decode",
                           params, mesh, cfg, sv)
        prompts = _prompts(3, cfg.vocab, seed=2)
        rids = [coord.submit(p, 6) for p in prompts]
        plan = chaos.FaultPlan(
            schedule={"corrupt:fleet.kv.pull": (0,)}, seed=7)
        with chaos.inject(plan):
            _run_workers([pre, dec])
        assert plan.fired("corrupt", "fleet.kv.pull") == 1
        _audit(coord, rids, prompts, 6, fleet_model)
        # quarantined bridge-wide + recomputed, no retry burned
        assert coord.bridge.store.n_quarantined >= 1
        # handoff and preemption both hand back their attempt, and the
        # corrupt pull recomputes same-attempt — so no completed
        # request shows a burned retry
        assert all(coord.queue.request(r).attempts == 1
                   for r in rids), \
            [(r, coord.queue.request(r).attempts) for r in rids]
        pre.close(); dec.close()
    finally:
        coord.shutdown()


def test_defective_engine_quarantined_work_reissued_bitwise(
        fleet_model, tmp_path):
    """'Host computes garbage': the victim engine's sealed KV page is
    corrupted in-arena (``serve.kv.page``); its completion fails the
    integrity re-verify, the IntegrityError fail RPC marks the engine
    defective, the coordinator quarantines it (claims denied) and
    force-reissues its in-flight work — the healthy engine completes
    everything bitwise."""
    params, mesh, cfg = fleet_model
    coord = Coordinator(tmp_path / "bridge", lease_s=10.0,
                        defect_threshold=1)
    try:
        # only the victim arms page integrity, so the process-global
        # chaos plan can only fire inside it
        victim = EngineWorker(coord.addr, "bad0", "both", params,
                              mesh, cfg,
                              ServeConfig(**SERVE_KW,
                                          integrity="pages"))
        prompts = _prompts(4, cfg.vocab, seed=3)
        rids = [coord.submit(p, 6) for p in prompts]
        plan = chaos.FaultPlan(
            schedule={"corrupt:serve.kv.page": (0,)}, seed=8)
        healthy = [None]

        def launch_healthy():
            # joins after the victim has had time to claim first
            time.sleep(0.3)
            healthy[0] = EngineWorker(coord.addr, "ok0", "both",
                                      params, mesh, cfg,
                                      ServeConfig(**SERVE_KW))
            healthy[0].run()

        t = threading.Thread(target=launch_healthy, daemon=True)
        with chaos.inject(plan):
            t.start()
            victim.run()
            t.join(timeout=180)
        assert not t.is_alive()
        assert plan.fired("corrupt", "serve.kv.page") >= 1
        _audit(coord, rids, prompts, 6, fleet_model)
        reg = coord.engines()
        assert reg["bad0"]["state"] == "quarantined", reg
        assert reg["bad0"]["defects"] >= 1
        # quarantined engines are denied claims
        cli = RpcClient(coord.addr)
        reply, _ = cli.call("claim", {"engine": "bad0"})
        assert reply["req"] is None and reply["denied"] == "quarantined"
        cli.close()
        victim.close()
        if healthy[0] is not None:
            healthy[0].close()
    finally:
        coord.shutdown()


def test_coordinator_restart_rewarms_from_persistent_bridge(
        fleet_model, tmp_path):
    """The bridge is a real on-disk PrefixStore: a restarted
    coordinator re-serves every block the previous life persisted,
    and a fresh engine's rewarm hook pulls the pending prompts' chains
    before serving — restored work is bitwise and the second life's
    prefill is mostly cache hits."""
    params, mesh, cfg = fleet_model
    store_dir = tmp_path / "bridge"
    prompts = _prompts(3, cfg.vocab, seed=4)
    sv = ServeConfig(**SERVE_KW)

    coord = Coordinator(store_dir, lease_s=10.0)
    w = EngineWorker(coord.addr, "life1", "both", params, mesh, cfg,
                     sv)
    rids = [coord.submit(p, 6) for p in prompts]
    _run_workers([w])
    _audit(coord, rids, prompts, 6, fleet_model)
    w.close()
    coord.shutdown()
    persisted = coord.bridge.store.n_blocks()
    assert persisted > 0

    # second life: same store dir, fresh coordinator + engine; the
    # SAME prompts are pending, so rewarm pulls their chains from the
    # bridge before the first claim
    coord2 = Coordinator(store_dir, lease_s=10.0)
    try:
        rids2 = [coord2.submit(p, 6) for p in prompts]
        w2 = EngineWorker(coord2.addr, "life2", "both", params, mesh,
                          cfg, sv, rewarm=True)
        _run_workers([w2])
        _audit(coord2, rids2, prompts, 6, fleet_model)
        # rewarm pulled the chains into the CACHED state before the
        # first claim, so serving sees device hits, not restores
        assert w2.rewarm_blocks > 0
        stats = w2.engine.prefix_stats()
        assert stats["hits"] >= len(prompts), stats
        w2.close()
    finally:
        coord2.shutdown()


def test_trace_tree_continuous_across_cross_engine_reissue(
        fleet_model, tmp_path):
    """One request, ONE tree: engine A dies mid-decode
    (``fleet.engine.die``), the reaper abandons its spans and the
    next attempt opens with the ``reissued_from`` edge; engine B's
    spans ride the SAME trace id (it rode the claim RPC), so the
    exported trace validates and holds exactly one tree per request."""
    params, mesh, cfg = fleet_model
    coord = Coordinator(tmp_path / "bridge", lease_s=0.4,
                        reap_interval_s=0.05)
    tb = obs.start_tracing()
    try:
        sv = ServeConfig(**SERVE_KW)
        prompts = _prompts(2, cfg.vocab, seed=5)
        rids = [coord.submit(p, 8) for p in prompts]
        plan = chaos.FaultPlan(
            schedule={"die:fleet.engine.die": (3,)}, seed=9)
        va = EngineWorker(coord.addr, "dies", "both", params, mesh,
                          cfg, sv)
        with chaos.inject(plan):
            with pytest.raises(chaos.InjectedDeath):
                va.run()
        assert plan.fired("die", "fleet.engine.die") == 1
        vb = EngineWorker(coord.addr, "lives", "both", params, mesh,
                          cfg, sv)
        _run_workers([vb])
        _audit(coord, rids, prompts, 8, fleet_model)
        assert coord.queue.n_reissues >= 1
        va.close(); vb.close()
    finally:
        obs.stop_tracing()
        coord.shutdown()
    # validate like export does: the dead engine's thread spans are
    # the abandoned-straggler case close_dangling exists for
    events = list(tb.events)
    events += obs.chrome.close_dangling(events)
    errors = obs.validate_trace(obs.chrome.to_chrome(events))
    assert errors == [], errors[:5]
    trees = trace_ctx.request_trees(events)
    assert len(trees) == len(rids)
    reissued = [ev for evs in trees.values() for ev in evs
                if ev.get("ph") == "b"
                and ev.get("name") == "serve.req.attempt"
                and "reissued_from" in (ev.get("args") or {})]
    assert reissued, "no reissued_from edge in any request tree"


# -- cache-aware routing (r20) ---------------------------------------

def test_routed_dispatch_stays_bitwise(fleet_model, tmp_path):
    """Routing changes WHERE a claim lands, never what it computes:
    a routed 2-engine run commits tokens bitwise identical to the
    single-request decode (hence to the blind run, which carries the
    same pin), and the route counters account for every decision."""
    params, mesh, cfg = fleet_model
    coord = Coordinator(tmp_path / "bridge", lease_s=10.0,
                        route_block_size=4)
    try:
        sv = ServeConfig(**SERVE_KW)
        workers = [EngineWorker(coord.addr, f"e{i}", "both",
                                params, mesh, cfg, sv,
                                report_interval_s=0.05)
                   for i in range(2)]
        prompts = _prompts(6, cfg.vocab, seed=6)
        rids = [coord.submit(p, 6) for p in prompts]
        _run_workers(workers)
        _audit(coord, rids, prompts, 6, fleet_model)
        # every granted claim went through the routed predicate
        assert coord.n_route_hits + coord.n_route_misses \
            + coord.n_route_escaped >= len(rids)
        for w in workers:
            w.close()
    finally:
        coord.shutdown()


def test_steered_claim_prefers_resident_engine_then_escapes(tmp_path):
    """The routing policy at the RPC surface, no engines: the engine
    whose heartbeat bloom holds the request's chain wins the claim;
    the cold engine is passed over (entry re-pushed untouched — its
    claim generation does not burn) until the starvation escape hatch
    makes the request claimable by anyone."""
    coord = Coordinator(tmp_path / "bridge", lease_s=10.0,
                        route_block_size=4, route_escape_rounds=2,
                        route_escape_s=30.0)
    try:
        cli = RpcClient(coord.addr)
        cli.call("hello", {"engine": "hot", "role": "both"})
        cli.call("hello", {"engine": "cold", "role": "both"})
        prompt = np.arange(8, dtype=np.int32)
        chains = block_hashes(prompt, 4, side="fp")
        assert len(chains) == 2
        cli.call("report", {"engine": "hot",
                            "resident": chain_bloom(chains)})
        cli.call("report", {"engine": "cold",
                            "resident": chain_bloom([])})
        rid = coord.submit(prompt, 3)
        # cold asks first but is steered away ...
        reply, _ = cli.call("claim", {"engine": "cold"})
        assert reply["req"] is None
        assert coord.n_route_steered == 1
        # ... and hot wins it with an UNTOUCHED generation: the
        # pass-over re-pushed the entry, seq still 1 (claim fencing
        # is unchanged under steering)
        reply, _ = cli.call("claim", {"engine": "hot"})
        assert reply["req"]["rid"] == rid
        assert reply["req"]["claim_seq"] == 1
        assert coord.n_route_hits == 1
        reply, _ = cli.call("complete", {
            "engine": "hot", "rid": rid, "seq": 1,
            "tokens": [1, 2, 3], "marks": {}})
        assert reply["committed"] is True
        # second request, same chain: hot never polls this time —
        # after route_escape_rounds pass-overs the cold engine gets
        # it anyway (routing is a preference, not a constraint)
        rid2 = coord.submit(prompt, 3)
        for _ in range(2):
            reply, _ = cli.call("claim", {"engine": "cold"})
            assert reply["req"] is None
        reply, _ = cli.call("claim", {"engine": "cold"})
        assert reply["req"]["rid"] == rid2
        assert reply["req"]["claim_seq"] == 1
        assert coord.n_route_escaped == 1
        reply, _ = cli.call("complete", {
            "engine": "cold", "rid": rid2, "seq": 1,
            "tokens": [1, 2, 3], "marks": {}})
        assert reply["committed"] is True
        assert coord.queue.n_duplicate_commits == 0
        cli.close()
    finally:
        coord.shutdown()


@pytest.mark.chaos
def test_corrupt_resident_bloom_misroutes_never_miscomputes(
        fleet_model, tmp_path):
    """The r20 telemetry drill with routing armed: a corrupted
    heartbeat bloom (``corrupt:fleet.telemetry.send`` on the summary
    hex) can at worst mis-route a claim — the malformed summary
    scores the engine cold, routing degrades toward blind dispatch —
    and every committed token stays bitwise the single-request
    decode."""
    params, mesh, cfg = fleet_model
    coord = Coordinator(tmp_path / "bridge", lease_s=10.0,
                        route_block_size=4)
    try:
        sv = ServeConfig(**SERVE_KW)
        workers = [EngineWorker(coord.addr, f"e{i}", "both",
                                params, mesh, cfg, sv,
                                report_interval_s=0.05)
                   for i in range(2)]
        prompts = _prompts(4, cfg.vocab, seed=7)
        rids = [coord.submit(p, 6) for p in prompts]
        plan = chaos.FaultPlan(
            schedule={"corrupt:fleet.telemetry.send": (0,)}, seed=11)
        with chaos.inject(plan):
            _run_workers(workers)
        assert plan.fired("corrupt", "fleet.telemetry.send") >= 1
        _audit(coord, rids, prompts, 6, fleet_model)
        for w in workers:
            w.close()
    finally:
        coord.shutdown()


# -- host-RAM bridge tier (r20) --------------------------------------

def _bridge_block():
    arrays = [np.arange(16, dtype=np.float32)]
    meta, blobs = encode_arrays(arrays)
    return arrays, meta, blobs


def test_ram_tier_fault_falls_back_to_disk(tmp_path):
    """``die:fleet.kv.pull`` on the RAM *hit* path: the poisoned host
    copy is evicted and the pull falls through to the disk tier —
    same digest, counted fault, and the disk hit re-promotes so the
    next pull is fast again."""
    bridge = BlockBridge(PrefixStore(tmp_path / "store"),
                         ram_blocks=8)
    _, meta, blobs = _bridge_block()
    bridge._put("e0", "h0", "fp", "digest0", meta, blobs)
    plan = chaos.FaultPlan(schedule={"die:fleet.kv.pull": (0,)},
                           seed=3)
    with chaos.inject(plan):
        reply, out = bridge._get("e1", "h0")
    assert plan.fired("die", "fleet.kv.pull") == 1
    assert reply["found"] and reply["digest"] == "digest0"
    assert out == blobs              # identical bytes from disk
    st = bridge.stats()
    assert st["ram_faults"] == 1 and st["disk_hits"] == 1 \
        and st["ram_hits"] == 0, st
    reply, _ = bridge._get("e1", "h0")   # promoted on the way out
    assert reply["found"]
    assert bridge.stats()["ram_hits"] == 1


def test_quarantine_purges_ram_tier_too(tmp_path):
    """Bridge-wide means EVERY tier: after a quarantine the RAM copy
    must be gone — no engine may be served suspect content from the
    fast path the disk purge didn't cover."""
    bridge = BlockBridge(PrefixStore(tmp_path / "store"),
                         ram_blocks=8)
    _, meta, blobs = _bridge_block()
    bridge._put("e0", "h0", "fp", "digest0", meta, blobs)
    reply, _ = bridge._get("e1", "h0")
    assert reply["found"] and bridge.stats()["ram_hits"] == 1
    bridge.handle("store.quarantine", {"h": "h0"}, ())
    reply, _ = bridge._get("e1", "h0")
    assert reply == {"found": False}
    assert bridge.stats()["ram_hits"] == 1   # no further RAM serve


def test_ram_lru_evicts_oldest_and_disk_still_serves(tmp_path):
    bridge = BlockBridge(PrefixStore(tmp_path / "store"),
                         ram_blocks=2)
    _, meta, blobs = _bridge_block()
    for i in range(3):
        bridge._put("e0", f"h{i}", "fp", f"d{i}", meta, blobs)
    # h0 was LRU-evicted from RAM; disk (the system of record) serves
    # it and the fetch counts as a disk hit
    reply, _ = bridge._get("e1", "h0")
    assert reply["found"]
    st = bridge.stats()
    assert st["disk_hits"] == 1 and st["ram_blocks"] == 2
    # write-through kept everything on disk
    assert st["blocks"] == 3


# -- cross-process weight cache (r20 scale-up TTFT) ------------------

def test_weight_cache_roundtrip_and_corrupt_fallback(tmp_path):
    """The scale-up TTFT fix: a joiner's ``build_model`` loads the
    deterministic recipe's host arrays from the digest-verified disk
    cache instead of re-initializing — bitwise the honest init — and
    a rotten cache file falls back to the honest rebuild (unlink, no
    error, same bytes)."""
    wc = str(tmp_path / "weights")
    fleet_worker._BUILD_MEMO.clear()
    params1, _, _ = build_model(dict(MODEL_SPEC), weight_cache=wc)
    files = list((tmp_path / "weights").glob("weights-*.npz"))
    assert len(files) == 1, files
    leaves1 = [np.asarray(x)
               for x in jax.tree_util.tree_leaves(params1)]
    # a fresh process (memo cleared) loads the SAME bytes from disk
    fleet_worker._BUILD_MEMO.clear()
    params2, _, _ = build_model(dict(MODEL_SPEC), weight_cache=wc)
    leaves2 = [np.asarray(x)
               for x in jax.tree_util.tree_leaves(params2)]
    assert len(leaves1) == len(leaves2)
    for a, b in zip(leaves1, leaves2):
        assert a.dtype == b.dtype and np.array_equal(a, b)
    # rot the cache: the loader must unlink and rebuild honestly
    files[0].write_bytes(b"not an npz")
    fleet_worker._BUILD_MEMO.clear()
    params3, _, _ = build_model(dict(MODEL_SPEC), weight_cache=wc)
    for a, b in zip(leaves1, jax.tree_util.tree_leaves(params3)):
        assert np.array_equal(a, np.asarray(b))


# -- scheduler handoff unit surface ----------------------------------

def test_handoff_extends_prompt_and_burns_no_retry():
    q = RequestQueue(lease_s=10.0)
    rid = q.submit(np.arange(5, dtype=np.int32), 4)
    req = q.claim()
    assert q.handoff(rid, [7], seq=req.claim_seq) == "queued"
    req = q.request(rid)
    assert req.state == "queued"
    assert list(req.prompt) == [0, 1, 2, 3, 4, 7]
    assert req.checksum == prompt_checksum(req.prompt)
    assert list(req.tokens) == [7]
    assert req.attempts == 0        # not a failure, like release
    # the decode claim sees the extended prompt and remaining budget
    req2 = q.claim()
    assert req2.rid == rid and req2.n_new == 4
    assert q.complete(rid, [7, 1, 2, 3], seq=req2.claim_seq)
    assert q.drained()


def test_handoff_finishes_on_exhaustion_and_eos():
    q = RequestQueue(lease_s=10.0)
    rid = q.submit(np.arange(4, dtype=np.int32), 1)
    req = q.claim()
    assert q.handoff(rid, [3], seq=req.claim_seq) == "done"
    assert q.request(rid).state == "done"
    assert q.drained()
    rid2 = q.submit(np.arange(4, dtype=np.int32), 8, eos_id=2)
    req2 = q.claim()
    assert q.handoff(rid2, [2], seq=req2.claim_seq) == "done"
    assert list(q.request(rid2).tokens) == [2]


def test_handoff_prefix_survives_reissue():
    """The soak's race, pinned deterministically: a decode-phase
    request reaped mid-decode must keep its handoff-committed
    token(s) — a requeue that cleared them would make the reissued
    claim decode one position too many and drop the handed-off token
    from the committed stream."""
    q = RequestQueue(lease_s=10.0)
    rid = q.submit(np.arange(5, dtype=np.int32), 4)
    req = q.claim()
    assert q.handoff(rid, [7], seq=req.claim_seq) == "queued"
    req2 = q.claim()
    assert req2.n_new - len(req2.tokens) == 3   # remaining budget
    q.expire([rid])                             # decode engine dies
    req3 = q.request(rid)
    assert list(req3.tokens) == [7], "handoff prefix lost on reap"
    req4 = q.claim()
    assert req4.n_new - len(req4.tokens) == 3
    assert q.complete(rid, [7, 1, 2, 3], seq=req4.claim_seq)


def test_handoff_stale_caller_fenced():
    q = RequestQueue(lease_s=10.0)
    rid = q.submit(np.arange(4, dtype=np.int32), 4)
    req = q.claim()
    q.expire([rid])
    assert q.request(rid).state == "queued"
    dups = q.n_duplicate_commits
    assert q.handoff(rid, [9], seq=req.claim_seq) == "stale"
    assert q.n_duplicate_commits == dups + 1
    assert list(q.request(rid).prompt) == [0, 1, 2, 3]


def test_claim_accept_predicate_preserves_order():
    q = RequestQueue(lease_s=10.0)
    r0 = q.submit(np.arange(3, dtype=np.int32), 2)
    r1 = q.submit(np.arange(3, dtype=np.int32), 2)
    # a filter that declines r0 must leave it queued, in place
    got = q.claim(accept=lambda r: r.rid != r0)
    assert got.rid == r1
    assert q.claim(accept=lambda r: r.rid != r0) is None
    got0 = q.claim()
    assert got0.rid == r0
