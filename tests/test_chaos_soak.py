"""Chaos soak drills: both long-running pipelines under injected worker
death, stragglers, bit-flips, and I/O faults — asserting the recovered
result is IDENTICAL to the fault-free run.

The solitaire solver is deterministic, so recovery is checked by exact
equality (the same oracle discipline as test_fuzz_collectives.py); the
train loop is checked by completing every step with a finite loss and
at least one recorded rollback. Everything is replayable: the fault
schedule is a pure function of the chaos plan, never of thread timing.

Marked slow + chaos (`make chaos`): each drill pays a fresh XLA
compile; tier-1 (`-m 'not slow'`) stays within budget.
"""

import json

import numpy as np
import pytest

import jax

from icikit import chaos
from icikit.models.solitaire import generate_dataset, solve_dynamic

pytestmark = [pytest.mark.slow, pytest.mark.chaos]


def _arrays(report):
    return (report.solved, report.n_moves, report.moves, report.steps,
            report.status)


def test_solve_dynamic_survives_death_of_all_but_one_worker():
    """The acceptance drill: p-1 of p workers die mid-run (plus
    straggler delays on the survivor); the survivor absorbs every
    reissued chunk and the report is bitwise-identical to fault-free."""
    p = 4
    assert jax.device_count() >= p
    devices = jax.devices()[:p]
    ds = generate_dataset(48, "easy", seed=17)

    baseline = solve_dynamic(ds, devices=devices, chunk_size=4)
    assert baseline.n_deaths == 0 and baseline.n_reissues == 0

    plan = chaos.FaultPlan(
        seed=5,
        # workers 1..3 claim their first pull, then crash; worker 0
        # limps (straggler sleeps) but survives and drains the queue
        schedule={f"die:solitaire.worker.{w}": (0,)
                  for w in range(1, p)},
        rates={"delay:solitaire.worker.0": 0.5},
        delay_s=0.005)
    with chaos.inject(plan):
        healed = solve_dynamic(ds, devices=devices, chunk_size=4)

    for a, b in zip(_arrays(baseline), _arrays(healed)):
        np.testing.assert_array_equal(a, b)   # exact, bitwise
    assert healed.n_deaths == p - 1
    assert healed.worker_deaths == [1, 2, 3]
    assert all("InjectedDeath" in e for e in healed.death_errors)
    assert healed.n_reissues > 0              # dead workers' leases
    assert sum(healed.per_worker_games) >= len(ds)
    assert healed.per_worker_games[0] > 0     # the survivor did work


def test_solve_dynamic_chaos_replays_bit_identically():
    """Same plan, same faults, same report: the whole drill is a pure
    function of (dataset, chunk plan, chaos seed)."""
    p = 2
    devices = jax.devices()[:p]
    ds = generate_dataset(24, "easy", seed=23)

    def drill():
        plan = chaos.FaultPlan(
            seed=9, rates={"delay:solitaire.worker.*": 0.3},
            schedule={"die:solitaire.worker.1": (1,)}, delay_s=0.005)
        with chaos.inject(plan):
            rep = solve_dynamic(ds, devices=devices, chunk_size=4)
        return rep, sorted(plan.log)

    rep1, log1 = drill()
    rep2, log2 = drill()
    assert log1 == log2
    for a, b in zip(_arrays(rep1), _arrays(rep2)):
        np.testing.assert_array_equal(a, b)
    assert rep1.n_deaths == rep2.n_deaths == 1


def test_solve_dynamic_death_plus_flaky_checkpoint(tmp_path):
    """Worker death AND flaky checkpoint storage at once: retried
    writes land, the run heals, and a restart trusts the file."""
    p = 3
    devices = jax.devices()[:p]
    ds = generate_dataset(36, "easy", seed=31)
    baseline = solve_dynamic(ds, devices=devices, chunk_size=4)

    ck = tmp_path / "chaos.ckpt"
    plan = chaos.FaultPlan(
        seed=2,
        schedule={"die:solitaire.worker.2": (0,)},
        # every ~5th write attempt fails; ChunkCheckpoint.add retries
        rates={"io:solitaire.ckpt.write": 0.2})
    with chaos.inject(plan):
        healed = solve_dynamic(ds, devices=devices, chunk_size=4,
                               checkpoint_path=str(ck))
    for a, b in zip(_arrays(baseline), _arrays(healed)):
        np.testing.assert_array_equal(a, b)
    assert healed.n_deaths == 1
    assert plan.fired("io") > 0               # the drill actually bit

    # a restart resumes every chunk from the survivor-written file
    resumed = solve_dynamic(ds, devices=devices, chunk_size=4,
                            checkpoint_path=str(ck))
    for a, b in zip(_arrays(baseline), _arrays(resumed)):
        np.testing.assert_array_equal(a, b)


def test_checked_collectives_soak_inside_solve_dynamic_run():
    """The checked-collective leg: one armed plan drives worker death,
    straggler delays AND in-schedule collective corruption through a
    solve_dynamic run with checked gradient-style collectives
    interleaved between the scheduler's pulls (the training-farm shape:
    work scheduling and checked syncs sharing one fault session).
    Every corruption the rate plan lands inside a schedule is detected
    and retried; the healed solve report and every collective result
    are bitwise identical to the fault-free run."""
    import jax.numpy as jnp

    from icikit.parallel.allgather import all_gather_blocks
    from icikit.parallel.allreduce import all_reduce
    from icikit.parallel import integrity
    from icikit.utils.mesh import make_mesh, shard_along

    p = 4
    devices = jax.devices()[:p]
    mesh = make_mesh(p)
    ds = generate_dataset(32, "easy", seed=41)
    rng = np.random.default_rng(41)
    payloads = [rng.integers(-1000, 1000, (p, 64)).astype(np.int32)
                for _ in range(6)]
    xs = [shard_along(jnp.asarray(d), mesh, "p") for d in payloads]

    def workload(checked):
        rep = solve_dynamic(ds, devices=devices, chunk_size=4)
        outs = []
        for i, x in enumerate(xs):
            fn = all_reduce if i % 2 else all_gather_blocks
            kw = {"checked": True, "retries": 6} if checked else {}
            outs.append(np.asarray(fn(x, mesh, algorithm="ring", **kw)))
        return rep, outs

    base_rep, base_outs = workload(checked=False)
    assert base_rep.n_deaths == 0

    integrity.reset_stats()
    plan = chaos.FaultPlan(
        seed=6,
        schedule={"die:solitaire.worker.3": (0,)},
        rates={"delay:solitaire.worker.*": 0.2,
               # every checked dispatch (and every retry) consults
               # this rate: over 6 collectives the drill fires
               # repeatedly, mid-schedule, while the farm is also
               # healing deaths; the widened retry budget above keeps
               # a fired-again retry a recovery, not an exhaustion
               "corrupt:collective.*": 0.5},
        delay_s=0.003)
    with chaos.inject(plan):
        healed_rep, healed_outs = workload(checked=True)

    # the farm healed bitwise...
    for a, b in zip(_arrays(base_rep), _arrays(healed_rep)):
        np.testing.assert_array_equal(a, b)
    assert healed_rep.n_deaths == 1
    # ...and every checked collective recovered bitwise too
    for a, b in zip(base_outs, healed_outs):
        np.testing.assert_array_equal(a, b)
    st = integrity.stats()
    fired = plan.fired("corrupt", "collective.*")
    assert fired > 0, "the corrupt rate never landed — dead drill"
    assert st["detected"] == fired  # every injected flip was caught
    assert st["recoveries"] > 0
    assert st["detected"] == st["retries"], (
        "every detection must recover within the retry budget")
    # replay determinism: the same plan reproduces the same fault log
    integrity.reset_stats()
    plan2 = chaos.FaultPlan(
        seed=6,
        schedule={"die:solitaire.worker.3": (0,)},
        rates={"delay:solitaire.worker.*": 0.2,
               "corrupt:collective.*": 0.5},
        delay_s=0.003)
    with chaos.inject(plan2):
        rep2, outs2 = workload(checked=True)
    assert (sorted(e for e in plan2.log if e[0] == "corrupt")
            == sorted(e for e in plan.log if e[0] == "corrupt"))
    for a, b in zip(healed_outs, outs2):
        np.testing.assert_array_equal(a, b)


def test_train_loop_checked_grad_sync_drill(capsys):
    """--checked-grad-sync end-to-end: an in-schedule flip in the
    gradient-sync digest ring (the corrupt:collective.train.grad_sync
    drill) surfaces as a device-guard anomaly at the fence — the step
    was skipped on device — and the run still completes finite."""
    from icikit.models.transformer.train import train

    plan = chaos.FaultPlan(
        # traced_corrupt_spec consults once per step: call index 3 ==
        # 1-based step 4
        schedule={"corrupt:collective.train.grad_sync": (3,)})
    with chaos.inject(plan):
        rc = train(["--steps", "8", "--batch", "4", "--vocab", "32",
                    "--d-model", "32", "--n-heads", "2", "--d-head", "8",
                    "--d-ff", "64", "--n-layers", "1", "--seq", "16",
                    "--dp", "2", "--compute-dtype", "float32",
                    "--log-every", "2", "--sample-tokens", "0",
                    "--guard-mode", "device", "--checked-grad-sync"])
    assert rc == 0
    assert plan.fired("corrupt", "collective.train.grad_sync") == 1
    recs = [json.loads(line) for line in
            capsys.readouterr().out.strip().splitlines()]
    anomalies = [r for r in recs if r.get("event") == "anomaly"]
    assert [a["step"] for a in anomalies] == [4]
    steps = [r for r in recs if "step" in r and "loss" in r]
    assert steps[-1]["step"] == 8
    assert np.isfinite(steps[-1]["loss"])
    summary = [r for r in recs if r.get("event") == "guard_summary"]
    assert summary and summary[0]["anomalies"] == 1


def test_train_loop_survives_nan_steps_and_flaky_ckpt(tmp_path, capsys):
    """Anomaly-guard drill: injected NaN losses are skipped, a streak
    triggers rollback to the last committed checkpoint, the first
    checkpoint save needs an I/O retry — and the run still completes
    every step with a finite final loss."""
    from icikit.models.transformer.train import train

    plan = chaos.FaultPlan(
        # probe call n at train.loss == 0-based step: corrupt steps
        # 5-6 (1-based) into NaN; rollback-after-2 fires on the second.
        # io @0: the step-3 checkpoint's first write attempt fails and
        # is retried (TrainCheckpointer backoff), not forfeited.
        schedule={"corrupt:train.loss": (4, 5),
                  "io:train.ckpt.save": (0,)},
        corrupt_mode="nan")
    with chaos.inject(plan):
        rc = train(["--steps", "12", "--batch", "4", "--vocab", "32",
                    "--d-model", "32", "--n-heads", "2", "--d-head", "8",
                    "--d-ff", "64", "--n-layers", "1", "--seq", "16",
                    "--compute-dtype", "float32", "--log-every", "3",
                    "--sample-tokens", "0", "--guard-rollback-after", "2",
                    "--ckpt-dir", str(tmp_path / "run"),
                    "--ckpt-every", "3"])
    assert rc == 0
    recs = [json.loads(line) for line in
            capsys.readouterr().out.strip().splitlines()]

    anomalies = [r for r in recs if r.get("event") == "anomaly"]
    rollbacks = [r for r in recs if r.get("event") == "rollback"]
    assert len(anomalies) == 2                # both injected NaNs seen
    assert len(rollbacks) == 1                # streak of 2 -> one rewind
    assert rollbacks[0]["to_step"] == 3       # last committed ckpt
    assert not any(r.get("event") == "ckpt_save_failed" for r in recs)

    steps = [r for r in recs if "step" in r and "loss" in r]
    assert steps[-1]["step"] == 12            # completed all steps
    assert np.isfinite(steps[-1]["loss"])     # and recovered

    summary = [r for r in recs if r.get("event") == "guard_summary"]
    assert summary and summary[0]["anomalies"] == 2
    assert summary[0]["rollbacks"] == 1
    assert summary[0]["ckpt_save_failures"] == 0
    assert plan.fired("io") == 1

    # determinism of the fault schedule itself: same plan, same log
    assert sorted(plan.log) == [("corrupt", "train.loss", 4),
                                ("corrupt", "train.loss", 5),
                                ("io", "train.ckpt.save", 0)]


def test_train_loop_rolls_back_to_start_without_ckpt(capsys):
    """No checkpoint dir: the guard's rollback target degrades to the
    start-of-run state, and the run still finishes finite."""
    from icikit.models.transformer.train import train

    plan = chaos.FaultPlan(
        schedule={"corrupt:train.loss": (2, 3, 4)}, corrupt_mode="nan")
    with chaos.inject(plan):
        rc = train(["--steps", "8", "--batch", "4", "--vocab", "32",
                    "--d-model", "32", "--n-heads", "2", "--d-head", "8",
                    "--d-ff", "64", "--n-layers", "1", "--seq", "16",
                    "--compute-dtype", "float32", "--log-every", "2",
                    "--sample-tokens", "0",
                    "--guard-rollback-after", "3"])
    assert rc == 0
    recs = [json.loads(line) for line in
            capsys.readouterr().out.strip().splitlines()]
    rollbacks = [r for r in recs if r.get("event") == "rollback"]
    assert len(rollbacks) == 1 and rollbacks[0]["to_step"] == 0
    steps = [r for r in recs if "step" in r and "loss" in r]
    assert steps[-1]["step"] == 8 and np.isfinite(steps[-1]["loss"])
