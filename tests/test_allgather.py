"""Pattern-oracle tests for the allgather family.

Ports the reference's self-verifying harness
(``Communication/src/main.cc:431-441``): fill send buffers with a
rank-and-iteration-derived arithmetic pattern, run the collective, assert
every device's received buffer matches the closed-form expectation.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from icikit.parallel import ALLGATHER_ALGORITHMS, all_gather_blocks
from icikit.utils.mesh import make_mesh, shard_along


def _pattern(p, m, it=0):
    """Rank-derived payload, same spirit as main.cc:431-433."""
    src = np.arange(p)[:, None]
    k = np.arange(m)[None, :]
    return (src * 1000 + k * 7 + it).astype(np.int32)


@pytest.mark.parametrize("algorithm", ALLGATHER_ALGORITHMS)
@pytest.mark.parametrize("m", [1, 16, 256])
def test_allgather_pattern_oracle(mesh8, algorithm, m):
    p = 8
    x = shard_along(jnp.asarray(_pattern(p, m)), mesh8)
    out = np.asarray(all_gather_blocks(x, mesh8, algorithm=algorithm))
    assert out.shape == (p, p, m)
    expected = _pattern(p, m)
    for d in range(p):  # every device verifies, as every rank did
        np.testing.assert_array_equal(out[d], expected)


@pytest.mark.parametrize("algorithm", ALLGATHER_ALGORITHMS)
def test_allgather_repeated_runs_stable(mesh8, algorithm):
    """The reference amplifies transient bugs by running test_runs times
    per size (main.cc:427-442)."""
    p, m = 8, 32
    for it in range(5):
        x = shard_along(jnp.asarray(_pattern(p, m, it)), mesh8)
        out = np.asarray(all_gather_blocks(x, mesh8, algorithm=algorithm))
        for d in range(p):
            np.testing.assert_array_equal(out[d], _pattern(p, m, it))


@pytest.mark.parametrize("algorithm",
                         ["naive", "ring", "xla", "recursive_doubling_twins"])
def test_allgather_non_power_of_two(algorithm):
    """ring/naive support any p; recursive_doubling_twins reproduces the
    reference's virtual-twin workaround (main.cc:71-75)."""
    p, m = 6, 8
    mesh = make_mesh(p)
    x = shard_along(jnp.asarray(_pattern(p, m)), mesh)
    out = np.asarray(all_gather_blocks(x, mesh, algorithm=algorithm))
    for d in range(p):
        np.testing.assert_array_equal(out[d], _pattern(p, m))


@pytest.mark.parametrize("p", [2, 3, 5, 6, 7, 8])
def test_recursive_doubling_twins_all_sizes(p):
    """The twin schedule must agree with the oracle at every device
    count, power-of-2 (where it defers to the plain schedule) or not."""
    m = 4
    mesh = make_mesh(p)
    x = shard_along(jnp.asarray(_pattern(p, m)), mesh)
    out = np.asarray(all_gather_blocks(
        x, mesh, algorithm="recursive_doubling_twins"))
    assert out.shape == (p, p, m)
    for d in range(p):
        np.testing.assert_array_equal(out[d], _pattern(p, m))


def test_recursive_doubling_rejects_non_pow2():
    mesh = make_mesh(6)
    x = shard_along(jnp.zeros((6, 4), jnp.int32), mesh)
    with pytest.raises(ValueError, match="power-of-2"):
        all_gather_blocks(x, mesh, algorithm="recursive_doubling")


@pytest.mark.parametrize("algorithm", ALLGATHER_ALGORITHMS)
def test_allgather_float_dtype(mesh4, algorithm):
    p, m = 4, 8
    rng = np.random.default_rng(0)
    data = rng.standard_normal((p, m)).astype(np.float32)
    x = shard_along(jnp.asarray(data), mesh4)
    out = np.asarray(all_gather_blocks(x, mesh4, algorithm=algorithm))
    for d in range(p):
        np.testing.assert_array_equal(out[d], data)
