"""Pallas save-stack writer (``icikit.ops.stack_write``): the kernel
pair (scalar-prefetch aliased write, matching read), the support gate,
and the explicit-stack rematerialized layer scan — gradient-parity-
pinned against the ``lax.scan`` path through the full model loss, in
interpret mode on CPU (the acceptance pin for the r6 save-stack
attempt; the measured TPU verdict lives in train_ab_r6.jsonl and
docs/DESIGN.md "Round-6")."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from icikit.ops.stack_write import (
    remat_scan_stacked,
    stack_read,
    stack_supported,
    stack_write,
)

RNG = np.random.default_rng(11)


# ------------------------------------------------------------- kernels

def test_stack_write_read_roundtrip():
    stack = jnp.asarray(RNG.standard_normal((4, 16, 128)).astype(np.float32))
    x = jnp.asarray(RNG.standard_normal((16, 128)).astype(np.float32))
    for i in (0, 2, 3):
        out = stack_write(stack, x, i, interpret=True)
        want = np.asarray(stack).copy()
        want[i] = np.asarray(x)
        np.testing.assert_array_equal(np.asarray(out), want)
        np.testing.assert_array_equal(
            np.asarray(stack_read(out, i, interpret=True)), np.asarray(x))
        # untouched slices survive the aliased in-place write
        for j in range(4):
            if j != i:
                np.testing.assert_array_equal(np.asarray(out[j]),
                                              np.asarray(stack[j]))


def test_stack_write_traced_index_under_jit():
    """The slice index is a scalar-prefetch operand: a traced i (the
    layer loop counter) must address the right slice."""
    stack = jnp.zeros((3, 8, 128), jnp.float32)
    x = jnp.ones((8, 128), jnp.float32)

    def loop(stack):
        return jax.lax.fori_loop(
            0, 3,
            lambda l, s: stack_write(s, x * (l + 1), l, interpret=True),
            stack)

    out = np.asarray(jax.jit(loop)(stack))
    for l in range(3):
        np.testing.assert_array_equal(out[l], np.full((8, 128), l + 1.0))


def test_stack_write_bf16_and_arbitrary_shape():
    # (b, s, d) slices flatten to the (rows, 128) view
    stack = jnp.zeros((2, 2, 8, 128), jnp.bfloat16)
    x = jnp.asarray(RNG.standard_normal((2, 8, 128)), jnp.bfloat16)
    out = stack_write(stack, x, 1, interpret=True)
    np.testing.assert_array_equal(
        np.asarray(out[1], np.float32), np.asarray(x, np.float32))
    got = stack_read(out, 1, interpret=True)
    assert got.shape == x.shape and got.dtype == x.dtype


def test_unsupported_slices_fall_back_to_xla():
    """Lane-indivisible or sublane-ragged slices take the
    dynamic-update-slice path — same semantics, no Mosaic tiling."""
    assert stack_supported((16, 128), jnp.float32)
    assert not stack_supported((5,), jnp.float32)      # not lane-divisible
    assert not stack_supported((9, 128), jnp.bfloat16)  # 9 % 16 rows
    stack = jnp.zeros((3, 5), jnp.float32)
    x = jnp.arange(5, dtype=jnp.float32)
    out = stack_write(stack, x, 2, interpret=True)
    np.testing.assert_array_equal(np.asarray(out[2]), np.asarray(x))
    np.testing.assert_array_equal(
        np.asarray(stack_read(out, 2, interpret=True)), np.asarray(x))


# ------------------------------------------- explicit-stack layer scan

def test_remat_scan_stacked_matches_scan_forward_and_grads():
    """Generic layer parity: stacked scan vs lax.scan on a synthetic
    layer (matmul + nonlinearity + aux), values and both gradient
    pytrees at fp32 tolerance."""
    L, D = 3, 64
    x0 = jnp.asarray(RNG.standard_normal((4, D)).astype(np.float32))
    lps = {"w": jnp.asarray(
        RNG.standard_normal((L, D, D)).astype(np.float32) / np.sqrt(D)),
        "b": jnp.asarray(RNG.standard_normal((L, D)).astype(np.float32))}
    positions = jnp.arange(4, dtype=jnp.int32)

    def layer(x, lp, positions):
        y = jnp.tanh(x @ lp["w"] + lp["b"])
        return x + y, jnp.sum(y * y).astype(jnp.float32)

    def loss_stacked(x0, lps):
        x, aux = remat_scan_stacked(layer, x0, lps, positions,
                                    interpret=True)
        return jnp.sum(x * x) + 0.1 * aux

    def loss_scan(x0, lps):
        def body(x, lp):
            x, a = layer(x, lp, positions)
            return x, a
        x, auxes = jax.lax.scan(body, x0, lps)
        return jnp.sum(x * x) + 0.1 * auxes.sum()

    v_s, g_s = jax.value_and_grad(loss_stacked, argnums=(0, 1))(x0, lps)
    v_r, g_r = jax.value_and_grad(loss_scan, argnums=(0, 1))(x0, lps)
    np.testing.assert_allclose(float(v_s), float(v_r), rtol=1e-6)
    for got, want in zip(jax.tree.leaves(g_s), jax.tree.leaves(g_r)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)


def test_remat_scan_stacked_xla_impl_matches():
    """impl="xla" (the A/B control: identical structure, dynamic-slice
    writes) produces the same values/grads as impl="pallas"."""
    L, D = 2, 32
    x0 = jnp.asarray(RNG.standard_normal((2, D)).astype(np.float32))
    lps = {"w": jnp.asarray(
        RNG.standard_normal((L, D, D)).astype(np.float32) / np.sqrt(D))}
    positions = jnp.arange(2, dtype=jnp.int32)

    def layer(x, lp, positions):
        return jnp.tanh(x @ lp["w"]), jnp.zeros((), jnp.float32)

    def loss(impl):
        def f(x0, lps):
            x, _ = remat_scan_stacked(layer, x0, lps, positions,
                                      impl=impl, interpret=True)
            return jnp.sum(x * x)
        return f

    vp, gp = jax.value_and_grad(loss("pallas"), argnums=(0, 1))(x0, lps)
    vx, gx = jax.value_and_grad(loss("xla"), argnums=(0, 1))(x0, lps)
    np.testing.assert_allclose(float(vp), float(vx), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(gp), jax.tree.leaves(gx)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)
    with pytest.raises(ValueError, match="save-stack impl"):
        remat_scan_stacked(layer, x0, lps, positions, impl="mosaic")


# ------------------------------------------------- full-model gradient pin

def _model_case():
    from icikit.models.transformer import TransformerConfig
    cfg = TransformerConfig(vocab=64, d_model=128, n_heads=4, d_head=8,
                            d_ff=64, n_layers=2, max_seq=32,
                            compute_dtype="float32")
    rng = np.random.default_rng(7)
    tok = rng.integers(0, cfg.vocab, (4, 32)).astype(np.int32)
    tgt = rng.integers(0, cfg.vocab, (4, 32)).astype(np.int32)
    return cfg, tok, tgt


def _run_loss(cfg, tok, tgt, dp=1, tp=1, sp=1):
    from jax.sharding import NamedSharding, PartitionSpec as P

    from icikit.models.transformer import init_params, loss_fn
    from icikit.models.transformer.model import make_model_mesh
    mesh = make_model_mesh(dp=dp, tp=tp, sp=sp)
    params = init_params(jax.random.key(0), cfg, mesh)
    sh = NamedSharding(mesh, P("dp", "sp"))
    loss, grads = loss_fn(params, jax.device_put(jnp.asarray(tok), sh),
                          jax.device_put(jnp.asarray(tgt), sh), mesh, cfg)
    return float(loss), jax.device_get(grads)


def test_model_save_stack_pallas_matches_xla_single_device():
    """The acceptance pin: the pallas save-stack training path's loss
    and full gradient pytree match the default lax.scan path at fp32
    tolerance (fused xent head active: d_model % 128 == 0)."""
    cfg, tok, tgt = _model_case()
    l_x, g_x = _run_loss(cfg, tok, tgt)
    l_p, g_p = _run_loss(dataclasses.replace(cfg, save_stack="pallas"),
                         tok, tgt)
    assert l_x == pytest.approx(l_p, rel=1e-5)
    for k in g_x:
        np.testing.assert_allclose(np.asarray(g_p[k]), np.asarray(g_x[k]),
                                   rtol=5e-4, atol=5e-5, err_msg=k)


@pytest.mark.parametrize("dp,tp,sp", [(2, 1, 1), (1, 2, 1), (1, 1, 2)])
def test_model_save_stack_pallas_matches_xla_sharded(dp, tp, sp):
    """Per-mesh parity: on every axis the stacked path must reproduce
    the scan path's gradients ON THE SAME MESH (the single-device
    cross-check is test_model_save_stack_pallas_matches_xla_single_
    device; cross-mesh replicated-leaf parity is a known jax-0.4.37
    env gap shared by both paths)."""
    if len(jax.devices()) < dp * tp * sp:
        pytest.skip("needs the simulated multi-device mesh")
    cfg, tok, tgt = _model_case()
    l_x, g_x = _run_loss(cfg, tok, tgt, dp, tp, sp)
    l_p, g_p = _run_loss(dataclasses.replace(cfg, save_stack="pallas"),
                         tok, tgt, dp, tp, sp)
    assert l_x == pytest.approx(l_p, rel=1e-5)
    for k in g_x:
        np.testing.assert_allclose(np.asarray(g_p[k]), np.asarray(g_x[k]),
                                   rtol=5e-4, atol=5e-5, err_msg=k)


def test_model_save_stack_validated():
    from icikit.models.transformer import TransformerConfig, param_specs
    with pytest.raises(ValueError, match="save_stack"):
        param_specs(TransformerConfig(save_stack="mosaic"))
