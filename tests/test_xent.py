"""Fused vocab-chunked cross-entropy head (``icikit.ops.xent``) vs the
unfused log-softmax oracle, through the Pallas interpreter on CPU.

The kernel streams vocab chunks with online max/sum-exp statistics;
these tests pin the fwd NLL, both cotangents (dx, dw), the multi-chunk
grid path (nt > 1, nv > 1), and the support gate the model layer uses
to choose between the fused and unfused heads.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from icikit.ops.xent import BLOCK_T, BLOCK_V, fused_xent, xent_supported

RNG = np.random.default_rng(17)


def _case(t, d, v):
    x = jnp.asarray(RNG.standard_normal((t, d)).astype(np.float32))
    w = jnp.asarray(RNG.standard_normal((v, d)).astype(np.float32) * 0.2)
    tgt = jnp.asarray(RNG.integers(0, v, size=t, dtype=np.int32))
    return x, w, tgt


def _oracle_nll(x, w, tgt):
    logits = (x.astype(jnp.float32) @ w.astype(jnp.float32).T)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, tgt[:, None], axis=1)[:, 0]


def test_fwd_matches_oracle():
    x, w, tgt = _case(256, 128, 512)
    got = fused_xent(x, w, tgt)
    want = _oracle_nll(x, w, tgt)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_fwd_multi_chunk_grid():
    # explicit small blocks force nt=2, nv=2 so the online max/sum-exp
    # carry and the iv==nv-1 flush actually run
    x, w, tgt = _case(512, 128, 1024)
    got = fused_xent(x, w, tgt, block_t=256, block_v=512)
    want = _oracle_nll(x, w, tgt)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_grads_match_oracle():
    x, w, tgt = _case(256, 128, 512)

    def fused_loss(x, w):
        return jnp.sum(fused_xent(x, w, tgt) * sel)

    def oracle_loss(x, w):
        return jnp.sum(_oracle_nll(x, w, tgt) * sel)

    # non-uniform cotangent so dnll scaling is exercised per token
    sel = jnp.asarray(RNG.standard_normal(256).astype(np.float32))
    dx_f, dw_f = jax.grad(fused_loss, argnums=(0, 1))(x, w)
    dx_o, dw_o = jax.grad(oracle_loss, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(dx_f), np.asarray(dx_o),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(dw_f), np.asarray(dw_o),
                               rtol=2e-4, atol=2e-4)


def test_grads_multi_chunk_bf16():
    x, w, tgt = _case(512, 128, 1024)
    x, w = x.astype(jnp.bfloat16), w.astype(jnp.bfloat16)

    def fused_loss(x, w):
        return jnp.mean(fused_xent(x, w, tgt, block_t=256, block_v=512))

    def oracle_loss(x, w):
        return jnp.mean(_oracle_nll(x, w, tgt))

    lf = fused_loss(x, w)
    lo = oracle_loss(x, w)
    np.testing.assert_allclose(float(lf), float(lo), rtol=2e-2)
    dx_f, dw_f = jax.grad(fused_loss, argnums=(0, 1))(x, w)
    dx_o, dw_o = jax.grad(oracle_loss, argnums=(0, 1))(x, w)
    assert dx_f.dtype == jnp.bfloat16 and dw_f.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(dx_f, np.float32),
                               np.asarray(dx_o, np.float32),
                               rtol=0.1, atol=0.05)
    np.testing.assert_allclose(np.asarray(dw_f, np.float32),
                               np.asarray(dw_o, np.float32),
                               rtol=0.1, atol=0.05)


def test_save_exp_fwd_identical_and_grads_match():
    """The save-exp head (r5: backward rebuilds softmax from saved
    bf16 exponentials instead of recomputing the logits chunk) must
    leave the forward bit-identical and the gradients equal to the
    recompute path up to the bf16 storage rounding of e. Multi-chunk
    blocks exercise the per-chunk running-max rescale — chunks written
    before the global max arrives are rescaled by exp2(m_i − lse)."""
    x, w, tgt = _case(512, 128, 1024)
    sel = jnp.asarray(RNG.standard_normal(512).astype(np.float32))

    def loss(save):
        def f(x, w):
            return jnp.sum(fused_xent(x, w, tgt, block_t=256,
                                      block_v=512, save_exp=save) * sel)
        return f

    np.testing.assert_array_equal(
        np.asarray(fused_xent(x, w, tgt, block_t=256, block_v=512,
                              save_exp=True)),
        np.asarray(fused_xent(x, w, tgt, block_t=256, block_v=512)))
    dx_s, dw_s = jax.grad(loss(True), argnums=(0, 1))(x, w)
    dx_r, dw_r = jax.grad(loss(False), argnums=(0, 1))(x, w)
    # fp32 x/w but e stored in x.dtype=fp32 here: rescale vs recompute
    # differ only by fp32 reassociation
    np.testing.assert_allclose(np.asarray(dx_s), np.asarray(dx_r),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dw_s), np.asarray(dw_r),
                               rtol=1e-4, atol=1e-5)
    # and against the oracle
    def oracle(x, w):
        return jnp.sum(_oracle_nll(x, w, tgt) * sel)
    dx_o, dw_o = jax.grad(oracle, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(dx_s), np.asarray(dx_o),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(dw_s), np.asarray(dw_o),
                               rtol=2e-4, atol=2e-4)


def test_save_exp_grads_bf16_storage_rounding():
    """bf16 x/w: e is stored bf16 (2^-8 relative), so saved-path
    gradients agree with the recompute path to bf16 tolerance."""
    x, w, tgt = _case(512, 128, 1024)
    x, w = x.astype(jnp.bfloat16), w.astype(jnp.bfloat16)

    def loss(save):
        def f(x, w):
            return jnp.mean(fused_xent(x, w, tgt, block_t=256,
                                       block_v=512, save_exp=save))
        return f

    dx_s, dw_s = jax.grad(loss(True), argnums=(0, 1))(x, w)
    dx_r, dw_r = jax.grad(loss(False), argnums=(0, 1))(x, w)
    assert dx_s.dtype == jnp.bfloat16 and dw_s.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(dx_s, np.float32),
                               np.asarray(dx_r, np.float32),
                               rtol=0.05, atol=0.02)
    np.testing.assert_allclose(np.asarray(dw_s, np.float32),
                               np.asarray(dw_r, np.float32),
                               rtol=0.05, atol=0.02)


@pytest.mark.parametrize("save", [False, True])
def test_fused_bwd_matches_matmul_bwd_and_oracle(save):
    """The r6 fused backward (dx/dw contracted in-kernel, no g matrix
    in HBM) must reproduce the matmul formulation and the oracle at
    fp32 tolerance — both flavors: recompute (g from a rebuilt logits
    chunk) and saved (g from the stored exponentials). Multi-chunk
    blocks exercise both accumulator grids (dx over the vocab grid,
    dw over the transposed token grid)."""
    x, w, tgt = _case(512, 128, 1024)
    sel = jnp.asarray(RNG.standard_normal(512).astype(np.float32))

    def loss(fuse):
        def f(x, w):
            return jnp.sum(fused_xent(x, w, tgt, block_t=256,
                                      block_v=512, save_exp=save,
                                      fused_bwd=fuse) * sel)
        return f

    dx_f, dw_f = jax.grad(loss(True), argnums=(0, 1))(x, w)
    dx_m, dw_m = jax.grad(loss(False), argnums=(0, 1))(x, w)
    # vs the matmul formulation: same g, same fp32 accumulation — only
    # reassociation differs
    np.testing.assert_allclose(np.asarray(dx_f), np.asarray(dx_m),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dw_f), np.asarray(dw_m),
                               rtol=1e-4, atol=1e-5)

    def oracle(x, w):
        return jnp.sum(_oracle_nll(x, w, tgt) * sel)

    dx_o, dw_o = jax.grad(oracle, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(dx_f), np.asarray(dx_o),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(dw_f), np.asarray(dw_o),
                               rtol=2e-4, atol=2e-4)


def test_fused_bwd_bf16_dtypes_and_tolerance():
    """bf16 operands through the fused backward: cotangents come out
    in the params' dtypes and match the matmul formulation to bf16
    storage tolerance."""
    x, w, tgt = _case(512, 128, 1024)
    x, w = x.astype(jnp.bfloat16), w.astype(jnp.bfloat16)

    def loss(fuse, save):
        def f(x, w):
            return jnp.mean(fused_xent(x, w, tgt, block_t=256,
                                       block_v=512, save_exp=save,
                                       fused_bwd=fuse))
        return f

    for save in (False, True):
        dx_f, dw_f = jax.grad(loss(True, save), argnums=(0, 1))(x, w)
        dx_m, dw_m = jax.grad(loss(False, save), argnums=(0, 1))(x, w)
        assert dx_f.dtype == jnp.bfloat16 and dw_f.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(dx_f, np.float32),
                                   np.asarray(dx_m, np.float32),
                                   rtol=0.05, atol=0.02)
        np.testing.assert_allclose(np.asarray(dw_f, np.float32),
                                   np.asarray(dw_m, np.float32),
                                   rtol=0.05, atol=0.02)


def test_supported_gate():
    assert xent_supported(1024, 128, 2048, jnp.bfloat16)
    assert xent_supported(256, 256, 512, jnp.float32)
    assert not xent_supported(256, 32, 512, jnp.float32)    # d % 128
    assert not xent_supported(1500, 128, 512, jnp.float32)  # T tiling
    assert not xent_supported(256, 128, 2500, jnp.float32)  # V tiling
    # any T/V <= block: the block shrinks to the array dim
    assert xent_supported(255, 128, 500, jnp.float32)
    assert not xent_supported(256, 128, 512, jnp.float16)   # dtype
    assert BLOCK_T % 8 == 0 and BLOCK_V % 128 == 0


def test_shape_mismatch_raises():
    x, w, tgt = _case(256, 128, 512)
    with pytest.raises(ValueError, match="shape mismatch"):
        fused_xent(x, w[:, :64], tgt)
    with pytest.raises(ValueError, match="fused xent needs"):
        fused_xent(x, w, tgt, block_t=100)  # 256 % 100 != 0
    # mixed operand dtypes would silently degrade the saved-flavor dw
    # through the narrower storage — rejected up front
    with pytest.raises(ValueError, match="dtype mismatch"):
        fused_xent(x.astype(jnp.bfloat16), w, tgt)


def test_sharded_dp_tokens():
    """The model calls the kernel inside shard_map with tokens sharded
    over dp and w replicated; pin that composition (vma accounting +
    per-shard grid) against the oracle."""
    from jax.sharding import PartitionSpec as P

    from icikit.parallel.shmap import shard_map
    from icikit.utils.mesh import make_mesh

    mesh = make_mesh()  # all visible devices on one axis
    axis = list(mesh.shape.keys())[0]
    p = mesh.shape[axis]
    t = 256 * p
    x, w, tgt = _case(t, 128, 512)

    def shard_fn(x, w, tgt):
        return fused_xent(x, w, tgt, interpret=True)

    nll = shard_map(shard_fn, mesh=mesh,
                    in_specs=(P(axis), P(), P(axis)),
                    out_specs=P(axis))(x, w, tgt)
    want = _oracle_nll(x, w, tgt)
    np.testing.assert_allclose(np.asarray(nll), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
