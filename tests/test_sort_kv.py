"""Distributed key-value sort / argsort vs the numpy stable oracle."""

import jax.numpy as jnp
import numpy as np
import pytest

from icikit.models.sort import argsort_dist, sort_kv
from icikit.utils.mesh import make_mesh


def _case(n, seed=0, dup_heavy=False, dtype=np.int32):
    rng = np.random.default_rng(seed)
    hi = 8 if dup_heavy else 10_000
    keys = rng.integers(-hi, hi, n).astype(dtype)
    vals = rng.integers(0, 1 << 30, n).astype(np.int32)
    return keys, vals


def _oracle(keys, vals):
    perm = np.argsort(keys, kind="stable")
    return keys[perm], vals[perm]


@pytest.mark.parametrize("splitter", ["allgather", "bitonic"])
@pytest.mark.parametrize("n", [256, 1000])  # 1000: padding path
def test_sort_kv_matches_stable_oracle(mesh8, splitter, n):
    keys, vals = _case(n, seed=1)
    ek, ev = _oracle(keys, vals)
    k, v = sort_kv(jnp.asarray(keys), jnp.asarray(vals), mesh8,
                   splitter=splitter)
    np.testing.assert_array_equal(np.asarray(k), ek)
    np.testing.assert_array_equal(np.asarray(v), ev)


def test_sort_kv_duplicate_keys_stable(mesh8):
    """Heavy duplicates: stability decides the value order — must match
    numpy's stable argsort exactly."""
    keys, vals = _case(512, seed=2, dup_heavy=True)
    ek, ev = _oracle(keys, vals)
    k, v = sort_kv(jnp.asarray(keys), jnp.asarray(vals), mesh8)
    np.testing.assert_array_equal(np.asarray(k), ek)
    np.testing.assert_array_equal(np.asarray(v), ev)


def test_sort_kv_max_keys_keep_values(mesh8):
    """Keys at the dtype max (the sentinel value) stay paired — the
    validity-flag design, not the sentinel trick."""
    keys = np.full(64, np.iinfo(np.int32).max, np.int32)
    keys[::3] = 7
    vals = np.arange(64, dtype=np.int32)
    ek, ev = _oracle(keys, vals)
    k, v = sort_kv(jnp.asarray(keys), jnp.asarray(vals), mesh8)
    np.testing.assert_array_equal(np.asarray(k), ek)
    np.testing.assert_array_equal(np.asarray(v), ev)


def test_sort_kv_float_keys(mesh8):
    rng = np.random.default_rng(3)
    keys = rng.standard_normal(300).astype(np.float32)
    vals = np.arange(300, dtype=np.int32)
    ek, ev = _oracle(keys, vals)
    k, v = sort_kv(jnp.asarray(keys), jnp.asarray(vals), mesh8)
    np.testing.assert_array_equal(np.asarray(k), ek)
    np.testing.assert_array_equal(np.asarray(v), ev)


def test_sort_kv_skewed_overflow_retry(mesh8):
    """All keys equal: every element routes to one bucket, far past the
    initial capacity — the safe-capacity retry must engage and the
    result stays exact."""
    keys = np.zeros(512, np.int32)
    vals = np.arange(512, dtype=np.int32)
    k, v = sort_kv(jnp.asarray(keys), jnp.asarray(vals), mesh8)
    np.testing.assert_array_equal(np.asarray(k), keys)
    np.testing.assert_array_equal(np.asarray(v), vals)


def test_argsort_dist(mesh8):
    keys, _ = _case(400, seed=4, dup_heavy=True)
    perm = np.asarray(argsort_dist(jnp.asarray(keys), mesh8))
    np.testing.assert_array_equal(perm, np.argsort(keys, kind="stable"))


def test_sort_kv_shape_mismatch(mesh8):
    with pytest.raises(ValueError, match="identical shapes"):
        sort_kv(jnp.zeros(8), jnp.zeros(9), mesh8)


def test_sort_kv_p1(mesh1):
    keys, vals = _case(128, seed=5)
    ek, ev = _oracle(keys, vals)
    k, v = sort_kv(jnp.asarray(keys), jnp.asarray(vals), mesh1)
    np.testing.assert_array_equal(np.asarray(k), ek)
    np.testing.assert_array_equal(np.asarray(v), ev)


def test_sort_kv_non_pow2_mesh():
    mesh = make_mesh(6)
    keys, vals = _case(300, seed=6)
    ek, ev = _oracle(keys, vals)
    k, v = sort_kv(jnp.asarray(keys), jnp.asarray(vals), mesh)
    np.testing.assert_array_equal(np.asarray(k), ek)
    np.testing.assert_array_equal(np.asarray(v), ev)
