"""Oracle tests for the reduce-scatter family (all schedules vs the
closed-form reduction, mirroring the allreduce oracles)."""

import jax.numpy as jnp
import numpy as np
import pytest

from icikit.parallel import reduce_scatter
from icikit.parallel.reducescatter import REDUCESCATTER_ALGORITHMS
from icikit.utils.mesh import UnsupportedMeshError, make_mesh, shard_along


def _data(p, m, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(-100, 100, size=(p, m)).astype(np.int32)


@pytest.mark.parametrize("algorithm", REDUCESCATTER_ALGORITHMS)
@pytest.mark.parametrize("chunk", [1, 8, 33])
def test_reduce_scatter_sum(mesh8, algorithm, chunk):
    p = 8
    data = _data(p, p * chunk)
    x = shard_along(jnp.asarray(data), mesh8)
    out = np.asarray(reduce_scatter(x, mesh8, algorithm=algorithm))
    expected = data.sum(axis=0).reshape(p, chunk)
    np.testing.assert_array_equal(out, expected)


@pytest.mark.parametrize("algorithm", REDUCESCATTER_ALGORITHMS)
@pytest.mark.parametrize("op,npop", [("max", np.max), ("min", np.min)])
def test_reduce_scatter_minmax(mesh8, algorithm, op, npop):
    p, chunk = 8, 4
    data = _data(p, p * chunk, seed=2)
    x = shard_along(jnp.asarray(data), mesh8)
    out = np.asarray(reduce_scatter(x, mesh8, algorithm=algorithm, op=op))
    expected = npop(data, axis=0).reshape(p, chunk)
    np.testing.assert_array_equal(out, expected)


@pytest.mark.parametrize("algorithm", ["ring", "pairwise", "xla"])
def test_reduce_scatter_non_pow2(algorithm):
    p, chunk = 6, 4
    mesh = make_mesh(p)
    data = _data(p, p * chunk, seed=3)
    x = shard_along(jnp.asarray(data), mesh)
    out = np.asarray(reduce_scatter(x, mesh, algorithm=algorithm))
    np.testing.assert_array_equal(out, data.sum(axis=0).reshape(p, chunk))


def test_recursive_halving_rejects_non_pow2():
    mesh = make_mesh(6)
    x = shard_along(jnp.asarray(_data(6, 12)), mesh)
    with pytest.raises(UnsupportedMeshError):
        reduce_scatter(x, mesh, algorithm="recursive_halving")


def test_reduce_scatter_2d_payload(mesh8):
    """Trailing dims ride along untouched (vectors of gradients)."""
    p, chunk, k = 8, 2, 5
    rng = np.random.default_rng(4)
    data = rng.standard_normal((p, p * chunk, k)).astype(np.float32)
    x = shard_along(jnp.asarray(data), mesh8)
    out = np.asarray(reduce_scatter(x, mesh8, algorithm="ring"))
    expected = data.sum(axis=0).reshape(p, chunk, k)
    np.testing.assert_allclose(out, expected, rtol=1e-5)


def test_reduce_scatter_p1(mesh1):
    data = _data(1, 8, seed=5)
    x = shard_along(jnp.asarray(data), mesh1)
    for alg in REDUCESCATTER_ALGORITHMS:
        out = np.asarray(reduce_scatter(x, mesh1, algorithm=alg))
        np.testing.assert_array_equal(out, data)


def test_harness_sweeps_reducescatter(mesh8):
    from icikit.bench.harness import sweep_family
    recs = sweep_family(mesh8, "reducescatter", sizes=[4], runs=2, warmup=1)
    assert {r.algorithm for r in recs} == set(REDUCESCATTER_ALGORITHMS)
    assert all(r.verified for r in recs)
