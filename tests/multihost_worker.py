"""Worker for the real 2-process ``jax.distributed`` bring-up test.

Each OS process simulates 4 CPU devices; together they form the 8-device
(dcn=2, ici=4) hybrid mesh. This is the ``mpirun`` analog executed for
real — the reference launches p ranks via PBS/mpirun
(``Communication/Data/sub.sh:9-15``, ``MPI_Init`` at
``Communication/src/main.cc:396``); here the coordinator handshake,
cross-process mesh construction and cross-process collectives all
actually run, not simulate.

Usage: python multihost_worker.py <coordinator_port> <process_id>
Prints "WORKER_OK" on success (the parent test asserts on it).
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402


def main() -> int:
    port, pid = int(sys.argv[1]), int(sys.argv[2])

    from icikit.parallel.multihost import (
        hierarchical_all_gather,
        hierarchical_all_reduce,
        init_distributed,
        make_hybrid_mesh,
        process_info,
    )

    # the MPI_Init analog — explicit coordinator, 2 processes
    assert init_distributed(coordinator_address=f"localhost:{port}",
                            num_processes=2, process_id=pid)
    assert init_distributed() is True  # idempotent second call
    rank, nproc, local = process_info()
    assert (rank, nproc, local) == (pid, 2, 4), (rank, nproc, local)
    assert jax.device_count() == 8

    # hybrid mesh across the two processes: outer axis = DCN
    mesh = make_hybrid_mesh()
    assert mesh.shape == {"dcn": 2, "p": 4}
    # outer axis must actually span the processes
    procs = [[d.process_index for d in row] for row in mesh.devices]
    assert sorted({p for row in procs for p in row}) == [0, 1]
    assert all(len(set(row)) == 1 for row in procs), procs

    p, m = 8, 16
    rng = np.random.default_rng(7)
    data = rng.integers(-100, 100, size=(p, m)).astype(np.int32)
    sharding = NamedSharding(mesh, P(("dcn", "p")))
    x = jax.make_array_from_callback(
        (p, m), sharding, lambda idx: data[idx])

    for alg in ("xla", "ring"):
        out = hierarchical_all_reduce(x, mesh, ici_algorithm=alg,
                                      dcn_algorithm=alg)
        want = data.sum(axis=0)
        for shard in out.addressable_shards:
            got = np.asarray(shard.data)
            assert (got == want[None].repeat(got.shape[0], 0)).all(), alg

    out = hierarchical_all_gather(x, mesh)
    for shard in out.addressable_shards:
        got = np.asarray(shard.data)  # (rows, p, m): all blocks per row
        assert (got == data[None]).all()

    # plain cross-process psum through the flat mesh path as well
    from icikit.parallel.shmap import shard_map

    def f(b):
        return jax.lax.psum(b, ("dcn", "p"))

    out = jax.jit(shard_map(f, mesh=mesh, in_specs=P(("dcn", "p")),
                            out_specs=P()))(x.astype(jnp.float32))
    np.testing.assert_allclose(
        np.asarray(out.addressable_shards[0].data),
        data.astype(np.float32).sum(axis=0)[None])

    print("WORKER_OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
