"""North-star runner smoke: all targets execute, verify, and render."""

import pytest

from icikit.bench.northstar import render_markdown, run_northstar


@pytest.mark.slow
def test_northstar_quick(mesh4):
    coll, sorts, dlb, checks = run_northstar(mesh4, quick=True, runs=2)
    assert checks["collectives_verified"]
    assert checks["sorts_verified"]
    assert checks["dlb_schedulers_agree"]
    assert {r.algorithm for r in sorts} == {
        "bitonic", "sample", "sample_bitonic", "quicksort"}
    assert {d["strategy"] for d in dlb} == {
        "static", "dynamic", "modeled-static", "modeled-dynamic"}
    # the skewed study: dynamic must spread the cost skew static
    # concentrates (per-worker DFS steps; machine-independent)
    assert checks["dlb_dynamic_balances_skew"]
    assert checks["dlb_dynamic_critical_path_win"]
    md = render_markdown(coll, sorts, dlb, checks,
                         {"platform": "cpu", "p": 4,
                          "date": "test", "wall_s": 0.0})
    assert "Target checks" in md and "PASS" in md
    assert "allreduce" in md and "bitonic" in md
