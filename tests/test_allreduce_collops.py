"""Oracle tests for allreduce / broadcast / scatter / gather."""

import jax.numpy as jnp
import numpy as np
import pytest

from icikit.parallel import all_reduce, broadcast, gather_blocks, scatter_blocks
from icikit.parallel.allreduce import ALLREDUCE_ALGORITHMS
from icikit.parallel.collops import (
    BROADCAST_ALGORITHMS,
    GATHER_ALGORITHMS,
    SCATTER_ALGORITHMS,
)
from icikit.utils.mesh import make_mesh, replicate, shard_along


def _data(p, m, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(-100, 100, size=(p, m)).astype(np.int32)


@pytest.mark.parametrize("algorithm", ALLREDUCE_ALGORITHMS)
@pytest.mark.parametrize("m", [8, 64, 100])  # 100: not divisible by p -> pad path
def test_allreduce_sum(mesh8, algorithm, m):
    p = 8
    data = _data(p, m)
    x = shard_along(jnp.asarray(data), mesh8)
    out = np.asarray(all_reduce(x, mesh8, algorithm=algorithm))
    expected = data.sum(axis=0)
    for d in range(p):
        np.testing.assert_array_equal(out[d], expected)


@pytest.mark.parametrize("algorithm", ALLREDUCE_ALGORITHMS)
@pytest.mark.parametrize("op,npop", [("max", np.max), ("min", np.min)])
def test_allreduce_minmax(mesh8, algorithm, op, npop):
    p, m = 8, 16
    data = _data(p, m, seed=2)
    x = shard_along(jnp.asarray(data), mesh8)
    out = np.asarray(all_reduce(x, mesh8, algorithm=algorithm, op=op))
    expected = npop(data, axis=0)
    for d in range(p):
        np.testing.assert_array_equal(out[d], expected)


@pytest.mark.parametrize("algorithm", BROADCAST_ALGORITHMS)
@pytest.mark.parametrize("root", [0, 3, 7])
def test_broadcast(mesh8, algorithm, root):
    p, m = 8, 32
    data = _data(p, m, seed=3)
    x = shard_along(jnp.asarray(data), mesh8)
    out = np.asarray(broadcast(x, mesh8, algorithm=algorithm, root=root))
    for d in range(p):
        np.testing.assert_array_equal(out[d], data[root])


@pytest.mark.parametrize("algorithm", SCATTER_ALGORITHMS)
@pytest.mark.parametrize("root", [0, 5])
def test_scatter(mesh8, algorithm, root):
    p, m = 8, 16
    data = _data(p, m, seed=4)
    x = replicate(jnp.asarray(data), mesh8)
    out = np.asarray(scatter_blocks(x, mesh8, algorithm=algorithm, root=root))
    np.testing.assert_array_equal(out, data)


@pytest.mark.parametrize("algorithm", GATHER_ALGORITHMS)
@pytest.mark.parametrize("root", [0, 2])
def test_gather(mesh8, algorithm, root):
    p, m = 8, 16
    data = _data(p, m, seed=5)
    x = shard_along(jnp.asarray(data), mesh8)
    out = np.asarray(gather_blocks(x, mesh8, algorithm=algorithm, root=root))
    np.testing.assert_array_equal(out[root], data)


@pytest.mark.parametrize("algorithm", BROADCAST_ALGORITHMS)
@pytest.mark.parametrize("root", [0, 2, 5])
def test_broadcast_non_pow2(algorithm, root):
    """All broadcast schedules support any p — including binomial, the
    default, whose perm-truncation path only triggers off powers of 2."""
    p, m = 6, 8
    mesh = make_mesh(p)
    data = _data(p, m, seed=6)
    x = shard_along(jnp.asarray(data), mesh)
    out = np.asarray(broadcast(x, mesh, algorithm=algorithm, root=root))
    for d in range(p):
        np.testing.assert_array_equal(out[d], data[root])


@pytest.mark.parametrize("algorithm", ["linear", "xla"])
def test_scatter_non_pow2(algorithm):
    p, m = 6, 8
    mesh = make_mesh(p)
    data = _data(p, m, seed=7)
    x = replicate(jnp.asarray(data), mesh)
    out = np.asarray(scatter_blocks(x, mesh, algorithm=algorithm, root=1))
    np.testing.assert_array_equal(out, data)


def test_p1_degenerate_mesh(mesh1):
    """p=1: every schedule degenerates to identity (zero-round loops)."""
    from icikit.parallel import all_gather_blocks, all_to_all_blocks
    data = _data(1, 8, seed=8)
    x = shard_along(jnp.asarray(data), mesh1)
    np.testing.assert_array_equal(
        np.asarray(all_gather_blocks(x, mesh1, algorithm="ring"))[0], data)
    np.testing.assert_array_equal(
        np.asarray(all_reduce(x, mesh1, algorithm="recursive_doubling")), data)
    np.testing.assert_array_equal(
        np.asarray(broadcast(x, mesh1, algorithm="binomial")), data)
    t = _data(1, 8, seed=9).reshape(1, 1, 8)
    xt = shard_along(jnp.asarray(t), mesh1)
    np.testing.assert_array_equal(
        np.asarray(all_to_all_blocks(xt, mesh1, algorithm="hypercube")), t)


def test_registry_lists_xla_everywhere():
    """Every family's vendor baseline is discoverable via the registry
    (one binary runs and compares all variants — SURVEY.md §5.6)."""
    from icikit.utils.registry import get_algorithm, list_algorithms
    for family in ("allgather", "alltoall", "allreduce", "broadcast",
                   "scatter", "gather", "scan"):
        assert "xla" in list_algorithms(family)
        assert get_algorithm(family, "xla") is not None
