"""Tests for the distributed sorts: the reference's inversion-count
oracle (psort.cc:497-520) plus exact-match against numpy, over uniform
and ODD_DIST-skewed inputs (the splitter/load-balance stressor)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from icikit.models.sort import SORT_ALGORITHMS, check_sort, sort
from icikit.models.sort.common import prepare_blocks
from icikit.ops.merge import bitonic_merge, compare_split_max, compare_split_min
from icikit.utils.mesh import make_mesh, shard_along
from icikit.utils.prandom import uniform_global


def _inputs(kind, n, seed=0):
    if kind == "uniform_f32":
        return np.asarray(uniform_global(jax.random.key(seed), n))
    if kind == "odd_dist":
        return np.asarray(uniform_global(jax.random.key(seed), n,
                                         odd_dist=True))
    if kind == "int32":
        rng = np.random.default_rng(seed)
        return rng.integers(-2**31, 2**31 - 1, size=n).astype(np.int32)
    if kind == "dups":
        rng = np.random.default_rng(seed)
        return rng.integers(0, 7, size=n).astype(np.int32)
    raise ValueError(kind)


def test_bitonic_merge_network():
    rng = np.random.default_rng(0)
    a = np.sort(rng.standard_normal(64).astype(np.float32))
    b = np.sort(rng.standard_normal(64).astype(np.float32))
    both = np.sort(np.concatenate([a, b]))
    lo = np.asarray(compare_split_min(jnp.asarray(a), jnp.asarray(b)))
    hi = np.asarray(compare_split_max(jnp.asarray(a), jnp.asarray(b)))
    np.testing.assert_array_equal(lo, both[:64])
    np.testing.assert_array_equal(hi, both[64:])
    # bitonic input sorts exactly
    v = np.concatenate([a, b[::-1]])
    np.testing.assert_array_equal(np.asarray(bitonic_merge(jnp.asarray(v))),
                                  np.sort(v))


@pytest.mark.parametrize("algorithm", SORT_ALGORITHMS)
@pytest.mark.parametrize("kind", ["uniform_f32", "odd_dist", "int32", "dups"])
def test_sort_matches_numpy(mesh8, algorithm, kind):
    n = 1 << 12
    data = _inputs(kind, n)
    out = np.asarray(sort(jnp.asarray(data), mesh8, algorithm=algorithm))
    np.testing.assert_array_equal(out, np.sort(data))


@pytest.mark.parametrize("algorithm", SORT_ALGORITHMS)
def test_sort_ragged_length(mesh8, algorithm):
    """Lengths not divisible by p exercise the sentinel-padding path."""
    n = 1000  # 1000 = 8*125, and bitonic pads n_loc 125 -> 128
    data = _inputs("int32", n, seed=3)
    out = np.asarray(sort(jnp.asarray(data), mesh8, algorithm=algorithm))
    np.testing.assert_array_equal(out, np.sort(data))


@pytest.mark.parametrize("algorithm", SORT_ALGORITHMS)
def test_sort_p4(mesh4, algorithm):
    n = 1 << 10
    data = _inputs("odd_dist", n, seed=5)
    out = np.asarray(sort(jnp.asarray(data), mesh4, algorithm=algorithm))
    np.testing.assert_array_equal(out, np.sort(data))


def test_sort_p1(mesh1):
    data = _inputs("int32", 100, seed=7)
    for alg in SORT_ALGORITHMS:
        out = np.asarray(sort(jnp.asarray(data), mesh1, algorithm=alg))
        np.testing.assert_array_equal(out, np.sort(data))


def test_sample_sort_overflow_retry(mesh8):
    """All-equal data lands in one bucket — the worst skew; the initial
    capacity overflows and the retry path must still sort correctly."""
    data = np.full(1 << 10, 42, np.int32)
    data[::7] = 41
    out = np.asarray(sort(jnp.asarray(data), mesh8, algorithm="sample"))
    np.testing.assert_array_equal(out, np.sort(data))


def test_quicksort_overflow_retry(mesh8):
    """All-equal data: every round's pivot equals every element, so one
    side of each partition absorbs nearly everything — the capacity must
    double (possibly twice) and the retried sort must still be exact."""
    data = np.full(1 << 10, 42, np.int32)
    out = np.asarray(sort(jnp.asarray(data), mesh8, algorithm="quicksort"))
    np.testing.assert_array_equal(out, np.sort(data))


def test_quicksort_irreducible_skew_raises(mesh8):
    """Skew beyond max_cap_factor must surface as RuntimeError, not a
    silently truncated result."""
    from icikit.models.sort.quicksort import hypercube_quicksort_blocks
    data = np.full(1 << 10, 7, np.int32)
    blocks, _ = prepare_blocks(jnp.asarray(data), mesh8)
    with pytest.raises(RuntimeError, match="skew"):
        hypercube_quicksort_blocks(blocks, mesh8, cap_factor=1.0,
                                   max_cap_factor=1.0)


def test_check_sort_counts_errors(mesh8):
    n = 1 << 10
    good = np.sort(_inputs("int32", n, seed=9))
    blocks, _ = prepare_blocks(jnp.asarray(good), mesh8)
    assert check_sort(blocks, mesh8) == 0
    bad = good.copy()
    bad[10], bad[500] = bad[500], bad[10]  # two cross-block inversions
    blocks_bad, _ = prepare_blocks(jnp.asarray(bad), mesh8)
    assert check_sort(blocks_bad, mesh8) > 0


def test_sort_rejects_unknown(mesh8):
    with pytest.raises(KeyError, match="unknown algorithm"):
        sort(jnp.zeros(16, jnp.int32), mesh8, algorithm="shellsort")


@pytest.mark.parametrize("algorithm", SORT_ALGORITHMS)
def test_sort_empty_input(mesh8, algorithm):
    out = np.asarray(sort(jnp.zeros((0,), jnp.int32), mesh8,
                          algorithm=algorithm))
    assert out.shape == (0,)


def test_sort_registry_lists_all():
    from icikit.utils.registry import list_algorithms
    assert set(list_algorithms("sort")) == set(SORT_ALGORITHMS)


def test_bitonic_non_pow2_mesh_raises():
    from icikit.utils.mesh import UnsupportedMeshError
    mesh = make_mesh(6)
    with pytest.raises(UnsupportedMeshError):
        sort(jnp.zeros(64, jnp.int32), mesh, algorithm="bitonic")


def test_default_capacities_hold_without_retry():
    """The measured defaults (capacity_study.json: sample cap_factor 4.0,
    quicksort 2.0) must clear an odd_dist workload on the first build —
    the retry path re-traces a whole new program, so the common case
    must never take it."""
    import jax
    from icikit.models.sort import quicksort as Q
    from icikit.models.sort import sample as S
    from icikit.utils.mesh import make_mesh, shard_along
    from icikit.utils.prandom import uniform_global

    p, n = 8, 1 << 16
    mesh = make_mesh(p)
    u = uniform_global(jax.random.key(0), n, odd_dist=True)
    keys = (u * 2e9 - 1e9).astype(jnp.int32)
    x2d = shard_along(keys.reshape(p, n // p), mesh)
    n_loc = n // p
    for splitter in ("allgather", "bitonic"):
        cap = max(1, min(n_loc, int(4.0 * n_loc / p)))   # the default
        _, ovf = S._build(mesh, "p", cap, splitter)(x2d)
        assert int(jax.device_get(ovf.sum())) == 0, splitter
    _, ovf = Q._build(mesh, "p", int(2.0 * n_loc))(x2d)  # the default
    assert int(jax.device_get(ovf.sum())) == 0
