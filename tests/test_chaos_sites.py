"""Chaos injection sites in the bench harness and multi-host launcher
— the two coverage gaps ROADMAP item 5c named.

Each drill proves (a) the probe fires where scheduled and (b) an
armed-but-never-firing plan leaves results byte-identical to an
unarmed run — injection sites must be free when cold.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from icikit import chaos
from icikit.bench.harness import sweep_collective
from icikit.parallel.multihost import (
    hierarchical_all_reduce,
    init_distributed,
    make_hybrid_mesh,
)
from icikit.utils.mesh import shard_along


# -- bench harness ---------------------------------------------------

def test_harness_die_site(mesh4):
    plan = chaos.FaultPlan(schedule={"die:bench.harness.*": (0,)})
    with chaos.inject(plan):
        with pytest.raises(chaos.InjectedDeath):
            sweep_collective(mesh4, "allgather", "xla", sizes=(4,),
                             runs=1, warmup=0)
        # the schedule index is consumed: a retry sails through
        recs = sweep_collective(mesh4, "allgather", "xla", sizes=(4,),
                                runs=1, warmup=0)
    assert plan.fired("die", "bench.harness.allgather") == 1
    assert recs[0].verified


def test_harness_verify_catches_injected_sdc(mesh4):
    """A flipped bit in the collective's output payload must flip
    `verified` to False — the closed-form check polices real bytes."""
    plan = chaos.FaultPlan(
        schedule={"corrupt:bench.harness.verify": (0,)})
    with chaos.inject(plan):
        bad = sweep_collective(mesh4, "allreduce", "ring", sizes=(16,),
                               runs=1, warmup=0)
        good = sweep_collective(mesh4, "allreduce", "ring",
                                sizes=(16,), runs=1, warmup=0)
    assert plan.fired("corrupt", "bench.harness.verify") == 1
    assert not bad[0].verified
    assert good[0].verified


def test_harness_clean_plan_identical_to_unarmed(mesh4):
    base = sweep_collective(mesh4, "allgather", "ring", sizes=(4, 16),
                            runs=1, warmup=0)
    plan = chaos.FaultPlan(rates={"die:bench.harness.*": 0.0,
                                  "corrupt:bench.harness.*": 0.0})
    with chaos.inject(plan):
        armed = sweep_collective(mesh4, "allgather", "ring",
                                 sizes=(4, 16), runs=1, warmup=0)
    assert plan.log == []
    for b, a in zip(base, armed):
        # everything but the timing fields must match exactly
        assert (b.family, b.algorithm, b.p, b.msize, b.dtype,
                b.bytes_per_block, b.verified) == \
               (a.family, a.algorithm, a.p, a.msize, a.dtype,
                a.bytes_per_block, a.verified)


# -- multi-host launcher ---------------------------------------------

def _hybrid_x(mesh, m, seed=0):
    p = mesh.devices.size
    rng = np.random.default_rng(seed)
    data = rng.integers(-100, 100, size=(p, m)).astype(np.int32)
    return data, shard_along(jnp.asarray(data), mesh,
                             axis_name=("dcn", "p"))


def test_multihost_init_die_site():
    plan = chaos.FaultPlan(schedule={"die:multihost.init": (0,)})
    with chaos.inject(plan):
        with pytest.raises(chaos.InjectedDeath):
            init_distributed()
        # retry: probe consumed; single-process env stays a no-op
        assert init_distributed() is False
    assert plan.fired("die", "multihost.init") == 1


def test_multihost_hier_die_site():
    mesh = make_hybrid_mesh(dcn_size=2, ici_size=2,
                            devices=jax.devices()[:4])
    _, x = _hybrid_x(mesh, 8)
    plan = chaos.FaultPlan(
        schedule={"die:multihost.hier.allreduce": (0,)})
    with chaos.inject(plan):
        with pytest.raises(chaos.InjectedDeath):
            hierarchical_all_reduce(x, mesh)
        out = np.asarray(hierarchical_all_reduce(x, mesh))
    assert plan.fired("die", "multihost.hier.allreduce") == 1
    assert out.shape == (4, 8)


def test_multihost_clean_plan_bitwise_identical():
    mesh = make_hybrid_mesh(dcn_size=2, ici_size=2,
                            devices=jax.devices()[:4])
    data, x = _hybrid_x(mesh, 8)
    base = np.asarray(hierarchical_all_reduce(x, mesh))
    plan = chaos.FaultPlan(rates={"die:multihost.*": 0.0,
                                  "delay:multihost.*": 0.0})
    with chaos.inject(plan):
        armed = np.asarray(hierarchical_all_reduce(x, mesh))
    assert plan.log == []
    np.testing.assert_array_equal(armed, base)
    np.testing.assert_array_equal(base[0], data.sum(axis=0))


def test_multihost_delay_sites_fire_without_changing_output():
    mesh = make_hybrid_mesh(dcn_size=2, ici_size=2,
                            devices=jax.devices()[:4])
    data, x = _hybrid_x(mesh, 8)
    base = np.asarray(hierarchical_all_reduce(x, mesh))
    plan = chaos.FaultPlan(rates={"delay:multihost.hier.*": 1.0},
                           delay_s=0.001)
    with chaos.inject(plan):
        delayed = np.asarray(hierarchical_all_reduce(x, mesh))
    assert plan.fired("delay", "multihost.hier.allreduce") == 1
    np.testing.assert_array_equal(delayed, base)
