"""Chaos injection sites in the bench harness and multi-host launcher
— the two coverage gaps ROADMAP item 5c named.

Each drill proves (a) the probe fires where scheduled and (b) an
armed-but-never-firing plan leaves results byte-identical to an
unarmed run — injection sites must be free when cold.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from icikit import chaos
from icikit.bench.harness import sweep_collective
from icikit.parallel.multihost import (
    hierarchical_all_reduce,
    init_distributed,
    make_hybrid_mesh,
)
from icikit.utils.mesh import shard_along


# -- bench harness ---------------------------------------------------

def test_harness_die_site(mesh4):
    plan = chaos.FaultPlan(schedule={"die:bench.harness.*": (0,)})
    with chaos.inject(plan):
        with pytest.raises(chaos.InjectedDeath):
            sweep_collective(mesh4, "allgather", "xla", sizes=(4,),
                             runs=1, warmup=0)
        # the schedule index is consumed: a retry sails through
        recs = sweep_collective(mesh4, "allgather", "xla", sizes=(4,),
                                runs=1, warmup=0)
    assert plan.fired("die", "bench.harness.allgather") == 1
    assert recs[0].verified


def test_harness_verify_catches_injected_sdc(mesh4):
    """A flipped bit in the collective's output payload must flip
    `verified` to False — the closed-form check polices real bytes."""
    plan = chaos.FaultPlan(
        schedule={"corrupt:bench.harness.verify": (0,)})
    with chaos.inject(plan):
        bad = sweep_collective(mesh4, "allreduce", "ring", sizes=(16,),
                               runs=1, warmup=0)
        good = sweep_collective(mesh4, "allreduce", "ring",
                                sizes=(16,), runs=1, warmup=0)
    assert plan.fired("corrupt", "bench.harness.verify") == 1
    assert not bad[0].verified
    assert good[0].verified


def test_harness_clean_plan_identical_to_unarmed(mesh4):
    base = sweep_collective(mesh4, "allgather", "ring", sizes=(4, 16),
                            runs=1, warmup=0)
    plan = chaos.FaultPlan(rates={"die:bench.harness.*": 0.0,
                                  "corrupt:bench.harness.*": 0.0})
    with chaos.inject(plan):
        armed = sweep_collective(mesh4, "allgather", "ring",
                                 sizes=(4, 16), runs=1, warmup=0)
    assert plan.log == []
    for b, a in zip(base, armed):
        # everything but the timing fields must match exactly
        assert (b.family, b.algorithm, b.p, b.msize, b.dtype,
                b.bytes_per_block, b.verified) == \
               (a.family, a.algorithm, a.p, a.msize, a.dtype,
                a.bytes_per_block, a.verified)


# -- fuzzers under delay plans (ROADMAP 5c remainder) ----------------
#
# The differential fuzzers already prove the schedules compute the
# right bytes; these drills re-run fuzzer-style random configurations
# with every dispatch-boundary delay probe firing (rate 1.0), to shake
# out schedule-dependent deadlocks: a straggling dispatch must finish
# (no hang — the test completing IS the assertion) and produce results
# byte-identical to the undelayed run.

def test_collective_fuzzer_under_delay_plan(mesh4):
    from icikit.bench.harness import sweep_collective
    rng = np.random.default_rng(5)
    fams = ("allgather", "allreduce", "alltoall", "reducescatter",
            "scan")
    picks = [(fams[rng.integers(len(fams))],
              int(rng.choice([4, 16, 64]))) for _ in range(6)]
    base = [sweep_collective(mesh4, fam, "xla", sizes=(m,), runs=1,
                             warmup=0)[0] for fam, m in picks]
    plan = chaos.FaultPlan(rates={"delay:bench.harness.*": 1.0},
                           delay_s=0.002)
    with chaos.inject(plan):
        delayed = [sweep_collective(mesh4, fam, "xla", sizes=(m,),
                                    runs=1, warmup=0)[0]
                   for fam, m in picks]
    assert plan.fired("delay", "bench.harness.*") == len(picks)
    for b, d in zip(base, delayed):
        assert d.verified
        assert (b.family, b.p, b.msize, b.verified) == \
               (d.family, d.p, d.msize, d.verified)


@pytest.mark.parametrize("algorithm", ["bitonic", "sample",
                                       "sample_bitonic", "quicksort"])
def test_sort_fuzzer_under_delay_plan(mesh4, algorithm):
    from icikit.models import sort as sort_mod
    rng = np.random.default_rng(11)
    xs = [jnp.asarray(rng.integers(-1000, 1000, size=int(n)), jnp.int32)
          for n in rng.choice([7, 64, 129, 500], size=3)]
    base = [np.asarray(sort_mod.sort(x, mesh4, algorithm=algorithm))
            for x in xs]
    plan = chaos.FaultPlan(rates={"delay:sort.*": 1.0}, delay_s=0.002)
    with chaos.inject(plan):
        delayed = [np.asarray(sort_mod.sort(x, mesh4,
                                            algorithm=algorithm))
                   for x in xs]
    assert plan.fired("delay", f"sort.{algorithm}") == len(xs)
    for x, b, d in zip(xs, base, delayed):
        np.testing.assert_array_equal(d, b)
        np.testing.assert_array_equal(b, np.sort(np.asarray(x)))


def test_sort_die_site_consumed_then_clean(mesh4):
    from icikit.models import sort as sort_mod
    x = jnp.asarray(np.random.default_rng(3).integers(0, 100, 64),
                    jnp.int32)
    plan = chaos.FaultPlan(schedule={"die:sort.bitonic": (0,)})
    with chaos.inject(plan):
        with pytest.raises(chaos.InjectedDeath):
            sort_mod.sort(x, mesh4, algorithm="bitonic")
        out = np.asarray(sort_mod.sort(x, mesh4, algorithm="bitonic"))
    assert plan.fired("die", "sort.bitonic") == 1
    np.testing.assert_array_equal(out, np.sort(np.asarray(x)))


# -- device-side SDC drills: checked collectives ---------------------
#
# The probes above corrupt at dispatch boundaries — arrays the host
# already holds. These drills flip a bit INSIDE the jitted schedule
# (chaos.traced_corrupt_spec -> transport.traced_flip, between two
# ppermute rounds) and prove the checked transport's contract: the
# flip is caught at the producing step, quarantined, and the bounded
# retry recovers a result bitwise identical to the uncorrupted run.

def _checked_call(family, alg, x, mesh, checked=True):
    from icikit.parallel.allgather import all_gather_blocks
    from icikit.parallel.allreduce import all_reduce
    from icikit.parallel.alltoall import all_to_all_blocks
    from icikit.parallel.reducescatter import reduce_scatter
    from icikit.parallel.scan import scan_reduce
    fns = {"allgather": all_gather_blocks, "allreduce": all_reduce,
           "alltoall": all_to_all_blocks,
           "reducescatter": reduce_scatter, "scan": scan_reduce}
    return fns[family](x, mesh, algorithm=alg, checked=checked)


def _checked_input(family, mesh4):
    p = 4
    rng = np.random.default_rng(13)
    if family == "alltoall":
        data = rng.integers(-1000, 1000, (p, p, 8)).astype(np.int32)
    elif family == "reducescatter":
        data = rng.integers(-1000, 1000, (p, p * 8)).astype(np.int32)
    else:
        data = rng.integers(-1000, 1000, (p, 16)).astype(np.int32)
    return shard_along(jnp.asarray(data), mesh4, "p")


@pytest.mark.parametrize("family,alg", [
    ("allgather", "ring"),
    ("allgather", "recursive_doubling"),
    ("allreduce", "ring"),
    ("allreduce", "recursive_doubling"),
    ("reducescatter", "ring"),
    ("reducescatter", "recursive_halving"),
    ("alltoall", "wraparound"),
    ("alltoall", "hypercube"),
    ("scan", "hillis_steele"),
])
def test_checked_collective_catches_in_schedule_flip(mesh4, family, alg):
    from icikit.parallel import integrity

    x = _checked_input(family, mesh4)
    base = np.asarray(_checked_call(family, alg, x, mesh4,
                                    checked=False))
    integrity.reset_stats()
    plan = chaos.FaultPlan(
        seed=21, schedule={f"corrupt:collective.{family}": (0,)})
    with chaos.inject(plan):
        healed = np.asarray(_checked_call(family, alg, x, mesh4))
    assert plan.fired("corrupt", f"collective.{family}") == 1
    st = integrity.stats()
    # caught at the step that produced it: exactly one (device, step)
    # cell of the verdict matrix flagged, then recovered by retry
    assert st["detected"] == 1 and st["recoveries"] == 1, st
    assert len(st["last"]["devices"]) == 1
    assert len(st["last"]["steps"]) == 1
    # and the recovered bytes are identical to the uncorrupted run
    np.testing.assert_array_equal(healed, base)


@pytest.mark.parametrize("family,alg", [
    ("allgather", "ring"), ("allreduce", "ring"),
    ("reducescatter", "ring"), ("alltoall", "wraparound"),
    ("scan", "hillis_steele"),
])
def test_checked_clean_armed_run_bit_identical(mesh4, family, alg):
    """The standing pin: an armed-but-never-firing corrupt plan leaves
    checked results byte-identical to unchecked unarmed runs — the
    checksum machinery must be free when cold."""
    from icikit.parallel import integrity

    x = _checked_input(family, mesh4)
    base = np.asarray(_checked_call(family, alg, x, mesh4,
                                    checked=False))
    integrity.reset_stats()
    plan = chaos.FaultPlan(rates={"corrupt:collective.*": 0.0})
    with chaos.inject(plan):
        armed = np.asarray(_checked_call(family, alg, x, mesh4))
    assert plan.log == []
    np.testing.assert_array_equal(armed, base)
    assert integrity.stats()["detected"] == 0  # zero false positives


def test_checked_sort_catches_in_schedule_flip(mesh4):
    from icikit.models import sort as sort_mod
    from icikit.parallel import integrity

    x = jnp.asarray(np.random.default_rng(3).integers(-1000, 1000, 129),
                    jnp.int32)
    base = np.asarray(sort_mod.sort(x, mesh4, algorithm="bitonic"))
    integrity.reset_stats()
    plan = chaos.FaultPlan(
        seed=9, schedule={"corrupt:sort.bitonic.exchange": (0,)})
    with chaos.inject(plan):
        healed = np.asarray(sort_mod.sort(x, mesh4, algorithm="bitonic",
                                          checked=True))
    assert plan.fired("corrupt", "sort.bitonic.exchange") == 1
    st = integrity.stats()
    assert st["detected"] == 1 and st["recoveries"] == 1, st
    np.testing.assert_array_equal(healed, base)
    np.testing.assert_array_equal(healed, np.sort(np.asarray(x)))


def test_checked_sort_clean_armed_bit_identical(mesh4):
    from icikit.models import sort as sort_mod
    from icikit.parallel import integrity

    x = jnp.asarray(np.random.default_rng(4).standard_normal(200),
                    jnp.float32)
    base = np.asarray(sort_mod.sort(x, mesh4, algorithm="bitonic"))
    integrity.reset_stats()
    plan = chaos.FaultPlan(rates={"corrupt:sort.*": 0.0})
    with chaos.inject(plan):
        armed = np.asarray(sort_mod.sort(x, mesh4, algorithm="bitonic",
                                         checked=True))
    assert plan.log == []
    np.testing.assert_array_equal(armed, base)
    assert integrity.stats()["detected"] == 0


# -- multi-host launcher ---------------------------------------------

def _hybrid_x(mesh, m, seed=0):
    p = mesh.devices.size
    rng = np.random.default_rng(seed)
    data = rng.integers(-100, 100, size=(p, m)).astype(np.int32)
    return data, shard_along(jnp.asarray(data), mesh,
                             axis_name=("dcn", "p"))


def test_multihost_init_die_site():
    plan = chaos.FaultPlan(schedule={"die:multihost.init": (0,)})
    with chaos.inject(plan):
        with pytest.raises(chaos.InjectedDeath):
            init_distributed()
        # retry: probe consumed; single-process env stays a no-op
        assert init_distributed() is False
    assert plan.fired("die", "multihost.init") == 1


def test_multihost_hier_die_site():
    mesh = make_hybrid_mesh(dcn_size=2, ici_size=2,
                            devices=jax.devices()[:4])
    _, x = _hybrid_x(mesh, 8)
    plan = chaos.FaultPlan(
        schedule={"die:multihost.hier.allreduce": (0,)})
    with chaos.inject(plan):
        with pytest.raises(chaos.InjectedDeath):
            hierarchical_all_reduce(x, mesh)
        out = np.asarray(hierarchical_all_reduce(x, mesh))
    assert plan.fired("die", "multihost.hier.allreduce") == 1
    assert out.shape == (4, 8)


def test_multihost_clean_plan_bitwise_identical():
    mesh = make_hybrid_mesh(dcn_size=2, ici_size=2,
                            devices=jax.devices()[:4])
    data, x = _hybrid_x(mesh, 8)
    base = np.asarray(hierarchical_all_reduce(x, mesh))
    plan = chaos.FaultPlan(rates={"die:multihost.*": 0.0,
                                  "delay:multihost.*": 0.0})
    with chaos.inject(plan):
        armed = np.asarray(hierarchical_all_reduce(x, mesh))
    assert plan.log == []
    np.testing.assert_array_equal(armed, base)
    np.testing.assert_array_equal(base[0], data.sum(axis=0))


def test_multihost_delay_sites_fire_without_changing_output():
    mesh = make_hybrid_mesh(dcn_size=2, ici_size=2,
                            devices=jax.devices()[:4])
    data, x = _hybrid_x(mesh, 8)
    base = np.asarray(hierarchical_all_reduce(x, mesh))
    plan = chaos.FaultPlan(rates={"delay:multihost.hier.*": 1.0},
                           delay_s=0.001)
    with chaos.inject(plan):
        delayed = np.asarray(hierarchical_all_reduce(x, mesh))
    assert plan.fired("delay", "multihost.hier.allreduce") == 1
    np.testing.assert_array_equal(delayed, base)
