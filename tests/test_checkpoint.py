"""Training checkpoint/resume tests: bitwise resume equivalence and
cross-mesh-layout restore (the capability the reference lacked,
SURVEY.md §5.4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from icikit.models.transformer import (
    TransformerConfig,
    init_params,
    make_train_step,
)
from icikit.models.transformer.model import make_model_mesh
from icikit.utils.checkpoint import TrainCheckpointer

CFG = TransformerConfig(vocab=31, d_model=16, n_heads=4, d_head=4,
                        d_ff=32, n_layers=2, max_seq=8,
                        compute_dtype="float32")


def _setup(mesh, seed=0):
    import optax
    params = init_params(jax.random.key(0), CFG, mesh)
    optimizer, step = make_train_step(mesh, CFG, optax.adam(1e-3))
    opt_state = optimizer.init(params)
    rng = np.random.default_rng(seed)
    sh = NamedSharding(mesh, P("dp", "sp"))
    tok = jax.device_put(
        jnp.asarray(rng.integers(0, CFG.vocab, (4, 8)), jnp.int32), sh)
    tgt = jax.device_put(
        jnp.asarray(rng.integers(0, CFG.vocab, (4, 8)), jnp.int32), sh)
    return params, optimizer, step, opt_state, tok, tgt


def test_resume_is_bitwise_equivalent(tmp_path):
    mesh = make_model_mesh(dp=2, tp=2, sp=2)
    params, optimizer, step, st, tok, tgt = _setup(mesh)

    for _ in range(3):
        params, st, _ = step(params, st, tok, tgt)
    with TrainCheckpointer(str(tmp_path / "ckpt")) as ck:
        ck.save(3, {"params": params, "opt": st})
        for _ in range(3):
            params, st, loss_a = step(params, st, tok, tgt)

        # resume from step 3 into freshly initialized state
        params_r, _, step_fn, st_r, _, _ = _setup(mesh)
        got_step, state = ck.restore({"params": params_r, "opt": st_r},
                                     mesh=mesh)
    assert got_step == 3
    params_r, st_r = state["params"], state["opt"]
    for _ in range(3):
        params_r, st_r, loss_b = step_fn(params_r, st_r, tok, tgt)
    assert float(loss_a) == float(loss_b)
    for k in params:
        np.testing.assert_array_equal(np.asarray(params[k]),
                                      np.asarray(params_r[k]))


def test_restore_onto_different_mesh_layout(tmp_path):
    mesh_a = make_model_mesh(dp=2, tp=2, sp=2)
    params_a = init_params(jax.random.key(7), CFG, mesh_a)
    with TrainCheckpointer(str(tmp_path / "ck2")) as ck:
        ck.save(0, {"params": params_a})

        mesh_b = make_model_mesh(dp=1, tp=4, sp=2)
        params_b = init_params(jax.random.key(8), CFG, mesh_b)  # target layout
        _, state = ck.restore({"params": params_b})
    for k in params_a:
        np.testing.assert_array_equal(np.asarray(params_a[k]),
                                      np.asarray(state["params"][k]))
        # equivalent placement, not object equality: the restore
        # normalizes trailing-None spec padding (init_params arrays
        # carry the padded spelling, jit outputs the stripped one —
        # restored state must match the WARM loop's avals so resume
        # does not recompile; see utils/checkpoint._abstract_like)
        assert state["params"][k].sharding.is_equivalent_to(
            params_b[k].sharding, params_b[k].ndim)


def test_restore_empty_dir_raises(tmp_path):
    with TrainCheckpointer(str(tmp_path / "empty")) as ck:
        with pytest.raises(FileNotFoundError):
            ck.restore({"x": jnp.zeros(3)})


def test_retention(tmp_path):
    with TrainCheckpointer(str(tmp_path / "keep"), max_to_keep=2) as ck:
        x = {"x": jnp.arange(4.0)}
        for s in (1, 2, 3, 4):
            ck.save(s, x)
        assert ck.latest_step() == 4
        steps = sorted(ck._mgr.all_steps())
    assert steps == [3, 4]


# --- trained-draft-head branch round-trip (optional param branch) ----

import dataclasses

DRAFT_CFG = dataclasses.replace(CFG, draft_head=True, draft_layers=1,
                                draft_rank=4)


def _draft_setup(mesh, cfg, lr=1e-3):
    from icikit.models.transformer.optim import make_optimizer
    params = init_params(jax.random.key(0), cfg, mesh)
    tx = make_optimizer(lr)
    _, step = make_train_step(mesh, cfg, tx)
    st = tx.init(params)
    rng = np.random.default_rng(0)
    sh = NamedSharding(mesh, P("dp", "sp"))
    tok = jax.device_put(
        jnp.asarray(rng.integers(0, cfg.vocab, (4, 8)), jnp.int32), sh)
    tgt = jax.device_put(
        jnp.asarray(rng.integers(0, cfg.vocab, (4, 8)), jnp.int32), sh)
    return params, step, st, tok, tgt


def test_draft_branch_roundtrip(tmp_path):
    """Save WITH the draft branch, restore strictly into a draft
    target: ordinary leaves, nothing special."""
    mesh = make_model_mesh(dp=2, tp=2, sp=2)
    params, step, st, tok, tgt = _draft_setup(mesh, DRAFT_CFG)
    params, st, _, _ = step(params, st, tok, tgt)
    with TrainCheckpointer(str(tmp_path / "d1")) as ck:
        ck.save(1, {"params": params, "opt": st})
        p_r, step2, st_r, _, _ = _draft_setup(mesh, DRAFT_CFG)
        got, state = ck.restore({"params": p_r, "opt": st_r}, mesh=mesh)
    assert got == 1
    for k in params:
        np.testing.assert_array_equal(np.asarray(params[k]),
                                      np.asarray(state["params"][k]))


def test_old_checkpoint_loads_into_draft_run(tmp_path):
    """The upgrade path: a PRE-DRAFT checkpoint restores into a
    --draft-head run with missing_ok — trunk (and its optimizer
    moments) come from the checkpoint, the head stays freshly
    initialized. Without missing_ok the mismatch still hard-fails."""
    mesh = make_model_mesh(dp=2, tp=2, sp=2)
    params0, step0, st0, tok, tgt = _draft_setup(mesh, CFG)
    for _ in range(2):
        params0, st0, _ = step0(params0, st0, tok, tgt)
    with TrainCheckpointer(str(tmp_path / "up")) as ck:
        ck.save(2, {"params": params0, "opt": st0})
        p_d, _, st_d, _, _ = _draft_setup(mesh, DRAFT_CFG)
        with pytest.raises(Exception):
            ck.restore({"params": p_d, "opt": st_d}, mesh=mesh)
        got, state = ck.restore({"params": p_d, "opt": st_d},
                                mesh=mesh, missing_ok=True)
    assert got == 2
    for k in params0:
        np.testing.assert_array_equal(np.asarray(params0[k]),
                                      np.asarray(state["params"][k]))
        np.testing.assert_array_equal(
            np.asarray(st0[0].mu[k]),
            np.asarray(state["opt"][0].mu[k]))
    for k in ("draft_ln", "draft_a", "draft_b"):
        np.testing.assert_array_equal(np.asarray(p_d[k]),
                                      np.asarray(state["params"][k]))
        assert not np.any(np.asarray(state["opt"][0].mu[k]))


def test_draft_checkpoint_loads_into_plain_run(tmp_path):
    """The downgrade path: a draft checkpoint restores into a plain
    trunk with missing_ok — the draft leaves are dropped."""
    mesh = make_model_mesh(dp=1, tp=2, sp=1)
    params_d, step_d, st_d, tok, tgt = _draft_setup(mesh, DRAFT_CFG)
    params_d, st_d, _, _ = step_d(params_d, st_d, tok, tgt)
    with TrainCheckpointer(str(tmp_path / "down")) as ck:
        ck.save(1, {"params": params_d, "opt": st_d})
        p0, _, st0, _, _ = _draft_setup(mesh, CFG)
        got, state = ck.restore({"params": p0, "opt": st0},
                                mesh=mesh, missing_ok=True)
    assert "draft_a" not in state["params"]
    for k in p0:
        np.testing.assert_array_equal(np.asarray(params_d[k]),
                                      np.asarray(state["params"][k]))


def test_resume_mid_distill_is_bitwise_equivalent(tmp_path):
    """2 distill steps + save + 2 more == save/restore + 2 — the head
    and its optimizer moments round-trip exactly (the draft analog of
    test_resume_is_bitwise_equivalent, on a SINGLE-device mesh: on
    this jax/XLA:CPU stack the jitted step's "replicated" outputs
    drift apart across dp replicas (docs/DESIGN.md "Pre-existing
    tier-1 failures"), so a save (which reads replica 0) + restore
    (which re-broadcasts it) cannot be bitwise on a multi-device mesh
    — that is the seed test's environmental failure, and this pin is
    about the draft BRANCH, not that drift)."""
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params, step, st, tok, tgt = _draft_setup(mesh, DRAFT_CFG)
    for _ in range(2):
        params, st, _, _ = step(params, st, tok, tgt)
    with TrainCheckpointer(str(tmp_path / "mid")) as ck:
        ck.save(2, {"params": params, "opt": st})
        for _ in range(2):
            params, st, loss_a, _ = step(params, st, tok, tgt)
        p_r, step2, st_r, _, _ = _draft_setup(mesh, DRAFT_CFG)
        _, state = ck.restore({"params": p_r, "opt": st_r}, mesh=mesh)
    p_r, st_r = state["params"], state["opt"]
    for _ in range(2):
        p_r, st_r, loss_b, _ = step2(p_r, st_r, tok, tgt)
    assert float(loss_a) == float(loss_b)
    for k in params:
        np.testing.assert_array_equal(np.asarray(params[k]),
                                      np.asarray(p_r[k]))