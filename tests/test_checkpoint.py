"""Training checkpoint/resume tests: bitwise resume equivalence and
cross-mesh-layout restore (the capability the reference lacked,
SURVEY.md §5.4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from icikit.models.transformer import (
    TransformerConfig,
    init_params,
    make_train_step,
)
from icikit.models.transformer.model import make_model_mesh
from icikit.utils.checkpoint import TrainCheckpointer

CFG = TransformerConfig(vocab=31, d_model=16, n_heads=4, d_head=4,
                        d_ff=32, n_layers=2, max_seq=8,
                        compute_dtype="float32")


def _setup(mesh, seed=0):
    import optax
    params = init_params(jax.random.key(0), CFG, mesh)
    optimizer, step = make_train_step(mesh, CFG, optax.adam(1e-3))
    opt_state = optimizer.init(params)
    rng = np.random.default_rng(seed)
    sh = NamedSharding(mesh, P("dp", "sp"))
    tok = jax.device_put(
        jnp.asarray(rng.integers(0, CFG.vocab, (4, 8)), jnp.int32), sh)
    tgt = jax.device_put(
        jnp.asarray(rng.integers(0, CFG.vocab, (4, 8)), jnp.int32), sh)
    return params, optimizer, step, opt_state, tok, tgt


def test_resume_is_bitwise_equivalent(tmp_path):
    mesh = make_model_mesh(dp=2, tp=2, sp=2)
    params, optimizer, step, st, tok, tgt = _setup(mesh)

    for _ in range(3):
        params, st, _ = step(params, st, tok, tgt)
    with TrainCheckpointer(str(tmp_path / "ckpt")) as ck:
        ck.save(3, {"params": params, "opt": st})
        for _ in range(3):
            params, st, loss_a = step(params, st, tok, tgt)

        # resume from step 3 into freshly initialized state
        params_r, _, step_fn, st_r, _, _ = _setup(mesh)
        got_step, state = ck.restore({"params": params_r, "opt": st_r},
                                     mesh=mesh)
    assert got_step == 3
    params_r, st_r = state["params"], state["opt"]
    for _ in range(3):
        params_r, st_r, loss_b = step_fn(params_r, st_r, tok, tgt)
    assert float(loss_a) == float(loss_b)
    for k in params:
        np.testing.assert_array_equal(np.asarray(params[k]),
                                      np.asarray(params_r[k]))


def test_restore_onto_different_mesh_layout(tmp_path):
    mesh_a = make_model_mesh(dp=2, tp=2, sp=2)
    params_a = init_params(jax.random.key(7), CFG, mesh_a)
    with TrainCheckpointer(str(tmp_path / "ck2")) as ck:
        ck.save(0, {"params": params_a})

        mesh_b = make_model_mesh(dp=1, tp=4, sp=2)
        params_b = init_params(jax.random.key(8), CFG, mesh_b)  # target layout
        _, state = ck.restore({"params": params_b})
    for k in params_a:
        np.testing.assert_array_equal(np.asarray(params_a[k]),
                                      np.asarray(state["params"][k]))
        assert state["params"][k].sharding == params_b[k].sharding


def test_restore_empty_dir_raises(tmp_path):
    with TrainCheckpointer(str(tmp_path / "empty")) as ck:
        with pytest.raises(FileNotFoundError):
            ck.restore({"x": jnp.zeros(3)})


def test_retention(tmp_path):
    with TrainCheckpointer(str(tmp_path / "keep"), max_to_keep=2) as ck:
        x = {"x": jnp.arange(4.0)}
        for s in (1, 2, 3, 4):
            ck.save(s, x)
        assert ck.latest_step() == 4
        steps = sorted(ck._mgr.all_steps())
    assert steps == [3, 4]