"""Grouped-query attention: config validation, parameter shapes,
cross-mesh training parity (dp/tp/sp and pipeline), and KV-cache decode
with the shrunken (n_kv_heads) cache vs the re-forward oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from icikit.models.transformer import (
    TransformerConfig,
    greedy_generate,
    init_params,
    loss_fn,
)
from icikit.models.transformer.model import make_model_mesh, repeat_kv

GQA_CFG = TransformerConfig(vocab=61, d_model=32, n_heads=8, d_head=8,
                            d_ff=64, n_layers=2, max_seq=32,
                            compute_dtype="float32", n_kv_heads=2)


def test_param_shapes():
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), GQA_CFG, mesh)
    assert "wqkv" not in params
    assert params["wq"].shape == (2, 32, 8, 8)
    assert params["wkv"].shape == (2, 32, 2, 2, 8)


def test_validation():
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    with pytest.raises(ValueError, match="must divide"):
        init_params(jax.random.key(0),
                    TransformerConfig(n_heads=4, n_kv_heads=3), mesh)


def test_repeat_kv():
    x = jnp.arange(2 * 3 * 2 * 4).reshape(2, 3, 2, 4)
    r = repeat_kv(x, 3)
    assert r.shape == (2, 3, 6, 4)
    np.testing.assert_array_equal(np.asarray(r[:, :, 0]),
                                  np.asarray(r[:, :, 2]))
    assert repeat_kv(x, 1) is x


@pytest.mark.parametrize("dp,tp,sp", [(1, 2, 2), (2, 2, 1)])
def test_gqa_training_cross_mesh_parity(dp, tp, sp):
    """tp shards K/V heads (n_kv_heads=2 over tp=2 -> 1 each); sharded
    loss/grads must equal the 1-device program."""
    rng = np.random.default_rng(0)
    tok = rng.integers(0, GQA_CFG.vocab, (4, 32)).astype(np.int32)
    tgt = rng.integers(0, GQA_CFG.vocab, (4, 32)).astype(np.int32)

    def run(dp, tp, sp):
        mesh = make_model_mesh(dp=dp, tp=tp, sp=sp)
        params = init_params(jax.random.key(0), GQA_CFG, mesh)
        sh = NamedSharding(mesh, P("dp", "sp"))
        loss, grads = loss_fn(params,
                              jax.device_put(jnp.asarray(tok), sh),
                              jax.device_put(jnp.asarray(tgt), sh),
                              mesh, GQA_CFG)
        return float(loss), jax.device_get(grads)

    l1, g1 = run(1, 1, 1)
    lp, gp = run(dp, tp, sp)
    assert l1 == pytest.approx(lp, rel=2e-5)
    for k in g1:
        np.testing.assert_allclose(np.asarray(gp[k]), np.asarray(g1[k]),
                                   atol=5e-5, rtol=5e-4, err_msg=k)


def test_gqa_pipeline_matches_flat():
    from icikit.models.transformer import (
        init_pp_params, make_pp_mesh, pp_loss_fn)
    rng = np.random.default_rng(1)
    tok = rng.integers(0, GQA_CFG.vocab, (2, 2, 32)).astype(np.int32)
    tgt = rng.integers(0, GQA_CFG.vocab, (2, 2, 32)).astype(np.int32)
    ppmesh = make_pp_mesh(dp=1, pp=2)
    pp_params = init_pp_params(jax.random.key(0), GQA_CFG, ppmesh)
    pl, _ = pp_loss_fn(pp_params, jnp.asarray(tok), jnp.asarray(tgt),
                       ppmesh, GQA_CFG, n_microbatches=2)

    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), GQA_CFG, mesh)
    flat_tok = jnp.asarray(tok.reshape(4, 32))
    flat_tgt = jnp.asarray(tgt.reshape(4, 32))
    fl, _ = loss_fn(params, flat_tok, flat_tgt, mesh, GQA_CFG)
    assert float(pl) == pytest.approx(float(fl), rel=2e-5)


def test_gqa_decode_matches_reforward():
    from icikit.models.attention.dense import dense_attention
    from icikit.models.transformer.model import _rms_norm

    mesh = make_model_mesh(dp=1, tp=2, sp=1)
    params = init_params(jax.random.key(0), GQA_CFG, mesh)
    rng = np.random.default_rng(2)
    prompt = rng.integers(0, GQA_CFG.vocab, (2, 6)).astype(np.int32)
    pd = jax.device_put(jnp.asarray(prompt),
                        NamedSharding(mesh, P("dp", None)))
    got = np.asarray(greedy_generate(params, pd, mesh, GQA_CFG, n_new=5))

    p = {k: jnp.asarray(np.asarray(v)) for k, v in params.items()}
    toks = jnp.asarray(prompt)
    n_rep = GQA_CFG.n_heads // GQA_CFG.n_kv_heads
    for _ in range(5):
        s = toks.shape[1]
        x = p["emb"][toks] + p["pos"][:s]
        for li in range(GQA_CFG.n_layers):
            h = _rms_norm(x, p["ln1"][li])
            q = jnp.einsum("bsd,dhe->bshe", h, p["wq"][li])
            kv = jnp.einsum("bsd,dthe->bsthe", h, p["wkv"][li])
            attn = dense_attention(q, repeat_kv(kv[:, :, 0], n_rep),
                                   repeat_kv(kv[:, :, 1], n_rep),
                                   causal=True)
            x = x + jnp.einsum("bshe,hed->bsd", attn, p["wo"][li])
            h2 = _rms_norm(x, p["ln2"][li])
            u = jax.nn.gelu(jnp.einsum("bsd,df->bsf", h2, p["w1"][li]))
            x = x + jnp.einsum("bsf,fd->bsd", u, p["w2"][li])
        x = _rms_norm(x, p["ln_f"])
        logits = jnp.einsum("bd,vd->bv", x[:, -1], p["w_out"])
        nxt = jnp.argmax(logits, axis=-1).astype(toks.dtype)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(got, np.asarray(toks))


def test_gqa_ulysses_sp_not_dividing_kv_heads():
    """sequence_schedule=ulysses with sp=4 and n_kv_heads=2 (sp does
    not divide h_kv): the kv-head-group split with per-device
    replication (icikit/models/attention/ulysses.py) must reproduce
    the 1-device loss/grads."""
    import numpy as np
    cfg = TransformerConfig(vocab=61, d_model=32, n_heads=8, d_head=8,
                            d_ff=64, n_layers=2, max_seq=32,
                            compute_dtype="float32", n_kv_heads=2,
                            sequence_schedule="ulysses")
    rng = np.random.default_rng(4)
    tok = rng.integers(0, cfg.vocab, (4, 32)).astype(np.int32)
    tgt = rng.integers(0, cfg.vocab, (4, 32)).astype(np.int32)

    def run(dp, tp, sp):
        mesh = make_model_mesh(dp=dp, tp=tp, sp=sp)
        params = init_params(jax.random.key(0), cfg, mesh)
        sh = NamedSharding(mesh, P("dp", "sp"))
        loss, grads = loss_fn(
            params, jax.device_put(jnp.asarray(tok), sh),
            jax.device_put(jnp.asarray(tgt), sh), mesh, cfg)
        return float(loss), grads

    l1, g1 = run(1, 1, 1)
    l4, g4 = run(1, 1, 4)
    np.testing.assert_allclose(l1, l4, rtol=1e-5)
    for k in g1:
        np.testing.assert_allclose(np.asarray(g1[k]), np.asarray(g4[k]),
                                   rtol=2e-4, atol=2e-5, err_msg=k)
