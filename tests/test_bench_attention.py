"""Attention-benchmark harness: record production, verification against
the dense oracle, table formatting — on the simulated 8-device mesh the
sequence-parallel schedules join the sweep (SURVEY.md §4.6)."""

import numpy as np

from icikit.bench.attention import (
    attention_flops,
    format_table,
    sweep_attention,
)


def test_local_sweep_fwd():
    recs = sweep_attention((64,), impls=("dense", "flash"), batch=1,
                           heads=2, d_head=16, dtype="float32",
                           mode="fwd", runs=2, warmup=1)
    assert [r.impl for r in recs] == ["dense", "flash"]
    assert all(r.verified for r in recs)
    assert all(r.best_s > 0 and np.isfinite(r.tflops) for r in recs)
    table = format_table(recs)
    assert "flash" in table and "✓" in table


def test_mesh_sweep_includes_schedules(mesh8):
    recs = sweep_attention((64,), batch=1, heads=8, d_head=16,
                           dtype="float32", mode="fwd", runs=1, warmup=1,
                           mesh=mesh8)
    impls = {r.impl for r in recs}
    assert {"dense", "flash", "ring", "ulysses", "zigzag"} <= impls
    assert all(r.verified for r in recs), [
        (r.impl, r.max_err) for r in recs]
    assert all(r.p == 8 for r in recs)


def test_flops_accounting():
    fwd = attention_flops(2, 128, 4, 32, causal=False, mode="fwd")
    assert fwd == 4.0 * 2 * 128 * 128 * 4 * 32
    assert attention_flops(2, 128, 4, 32, True, "fwd") == fwd / 2
    assert attention_flops(2, 128, 4, 32, False, "fwdbwd") == fwd * 3.5
