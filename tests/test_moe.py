"""Expert-parallel MoE tests: no-drop dense oracle, schedule parity,
and full-model integration (training smoke + tp-mesh parity)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from icikit.models.transformer import TransformerConfig, init_params, loss_fn
from icikit.models.transformer.model import make_model_mesh
from icikit.models.transformer.moe import moe_ffn_shard
from icikit.parallel.shmap import wrap_program

E, D, F = 8, 16, 32


def _weights(seed=0):
    rng = np.random.default_rng(seed)
    wr = rng.normal(0, 0.5, (D, E)).astype(np.float32)
    we1 = rng.normal(0, 0.2, (E, D, F)).astype(np.float32)
    we2 = rng.normal(0, 0.2, (E, F, D)).astype(np.float32)
    return wr, we1, we2


def _oracle(x, wr, we1, we2):
    """Per-token dense computation: every token to its argmax expert."""
    t = x.reshape(-1, D)
    probs = jax.nn.softmax(t @ wr, axis=-1)
    e = np.asarray(probs.argmax(axis=-1))
    gate = np.asarray(probs.max(axis=-1))
    out = np.stack([
        gate[i] * np.asarray(
            jax.nn.gelu(t[i] @ we1[e[i]]) @ we2[e[i]])
        for i in range(t.shape[0])])
    return out.reshape(x.shape)


def _run_sharded(x, wr, we1, we2, dp, algorithm, cf):
    mesh = make_model_mesh(dp=dp, tp=1, sp=1)

    def per_shard(x, wr, we1, we2):
        out, aux = moe_ffn_shard(x, wr, we1, we2, axis="dp", p=dp,
                                 n_experts=E, capacity_factor=cf,
                                 algorithm=algorithm)
        return out, aux[None]

    fn = wrap_program(
        per_shard, mesh,
        (P("dp"), P(), P("dp"), P("dp")),
        (P("dp"), P("dp")))
    xs = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("dp")))
    ws = jax.device_put(jnp.asarray(we1), NamedSharding(mesh, P("dp")))
    w2s = jax.device_put(jnp.asarray(we2), NamedSharding(mesh, P("dp")))
    out, aux = fn(xs, jnp.asarray(wr), ws, w2s)
    return np.asarray(out), np.asarray(aux)


@pytest.mark.parametrize("dp", [1, 2, 4])
@pytest.mark.parametrize("algorithm", ["xla", "wraparound"])
def test_moe_matches_dense_oracle_no_drop(dp, algorithm):
    """With capacity >= all tokens nothing drops: the sharded dispatch
    must equal dense per-token expert computation for any dp."""
    rng = np.random.default_rng(1)
    x = rng.normal(0, 1, (8, 4, D)).astype(np.float32)
    wr, we1, we2 = _weights()
    out, aux = _run_sharded(x, wr, we1, we2, dp, algorithm, cf=float(E))
    want = _oracle(x, wr, we1, we2)
    np.testing.assert_allclose(out, want, rtol=2e-4, atol=1e-5)
    assert np.all(np.isfinite(aux)) and np.all(aux >= 1.0 - 1e-5)


def test_moe_capacity_drops_are_zero():
    """Overflow tokens fall back to zero (residual passthrough), and
    shrinking capacity only ever zeroes outputs, never corrupts them."""
    rng = np.random.default_rng(2)
    x = rng.normal(0, 1, (8, 4, D)).astype(np.float32)
    wr, we1, we2 = _weights()
    full, _ = _run_sharded(x, wr, we1, we2, 2, "xla", cf=float(E))
    tight, _ = _run_sharded(x, wr, we1, we2, 2, "xla", cf=0.25)
    tok_full = full.reshape(-1, D)
    tok_tight = tight.reshape(-1, D)
    dropped = np.all(tok_tight == 0, axis=-1)
    assert dropped.any(), "tight capacity should drop some tokens"
    np.testing.assert_allclose(tok_tight[~dropped], tok_full[~dropped],
                               rtol=2e-4, atol=1e-5)


MOE_CFG = TransformerConfig(vocab=61, d_model=32, n_heads=4, d_head=8,
                            d_ff=64, n_layers=2, max_seq=16,
                            compute_dtype="float32", n_experts=8,
                            capacity_factor=2.0)


def _batch(b=8, s=16, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, MOE_CFG.vocab, (b, s)).astype(np.int32),
            rng.integers(0, MOE_CFG.vocab, (b, s)).astype(np.int32))


def _place(mesh, tok, tgt):
    sh = NamedSharding(mesh, P("dp", "sp"))
    return (jax.device_put(jnp.asarray(tok), sh),
            jax.device_put(jnp.asarray(tgt), sh))


def test_moe_model_tp_parity():
    """tp sharding must not change MoE model loss/grads (routing is a
    dp/sp-local decision)."""
    mesh1 = make_model_mesh(dp=1, tp=1, sp=1)
    mesh2 = make_model_mesh(dp=1, tp=4, sp=1)
    p1 = init_params(jax.random.key(0), MOE_CFG, mesh1)
    p2 = init_params(jax.random.key(0), MOE_CFG, mesh2)
    tok, tgt = _batch()
    l1, g1 = loss_fn(p1, *_place(mesh1, tok, tgt), mesh1, MOE_CFG)
    l2, g2 = loss_fn(p2, *_place(mesh2, tok, tgt), mesh2, MOE_CFG)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    for k in g1:
        np.testing.assert_allclose(np.asarray(g1[k]), np.asarray(g2[k]),
                                   rtol=2e-4, atol=2e-5, err_msg=k)


def test_moe_model_trains():
    import optax

    from icikit.models.transformer import make_train_step
    mesh = make_model_mesh(dp=2, tp=2, sp=2)
    params = init_params(jax.random.key(3), MOE_CFG, mesh)
    tok, tgt = _batch(seed=5)
    tok_d, tgt_d = _place(mesh, tok, tgt)
    optimizer, step = make_train_step(mesh, MOE_CFG, optax.adam(1e-2))
    st = optimizer.init(params)
    first = None
    for _ in range(30):
        params, st, loss = step(params, st, tok_d, tgt_d)
        if first is None:
            first = float(loss)
    assert float(loss) < first * 0.6, (first, float(loss))
    assert np.abs(np.asarray(params["we1"])).max() > 0