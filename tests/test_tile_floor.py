"""The d=64 per-tile floor decomposition bench runs and decomposes."""

from icikit.bench.tile_floor import measure, render


def test_tile_floor_variants_execute():
    """All three variants execute (interpret mode on CPU) and produce
    per-tile numbers; the render names each variant."""
    recs = measure(seq=2048, d=64, h=1, bq=512, bk=512, windows=1)
    assert {r["variant"] for r in recs} == {
        "full", "mxu", "softmax_ks1", "no_exp2", "no_max",
        "no_exp2_no_max"}
    assert all(r["per_tile_us"] > 0 for r in recs)
    text = render(recs)
    assert "mxu-only" in text and "exp2" in text and "rowmax" in text
