"""Smoke tests for the benchmark harness and driver entry points."""

import json
import subprocess
import sys

import jax.numpy as jnp

from icikit.bench.harness import format_table, sweep_collective, sweep_family


def test_sweep_collective_verifies(mesh4):
    recs = sweep_collective(mesh4, "allgather", "ring", sizes=[4, 16],
                            runs=2, warmup=1)
    assert len(recs) == 2
    assert all(r.verified for r in recs)
    assert all(r.busbw_gbps > 0 for r in recs)
    assert json.loads(recs[0].to_json())["family"] == "allgather"


def test_sweep_family_skips_constrained(mesh4):
    recs = sweep_family(mesh4, "alltoall", sizes=[4], runs=1, warmup=1)
    algs = {r.algorithm for r in recs}
    assert {"wraparound", "naive", "ecube", "hypercube", "xla"} <= algs
    assert all(r.verified for r in recs)
    table = format_table(recs)
    assert "hypercube" in table


def test_sweep_allreduce_all_variants(mesh4):
    recs = sweep_family(mesh4, "allreduce", sizes=[16], runs=1, warmup=1)
    assert {r.algorithm for r in recs} == {"recursive_doubling", "ring", "xla"}
    assert all(r.verified for r in recs)


def test_graft_entry_single_chip():
    import __graft_entry__
    import jax
    import numpy as np

    fn, args = __graft_entry__.entry()
    out = jax.block_until_ready(jax.jit(fn)(*args))
    arr = np.asarray(out).ravel()
    assert (np.diff(arr) >= 0).all()


def test_graft_dryrun_multichip():
    import __graft_entry__
    __graft_entry__.dryrun_multichip(8)


def test_profile_flag_writes_trace(tmp_path):
    """--profile captures a jax.profiler trace directory (SURVEY §5.1
    upgrade: per-collective tracing the reference lacked)."""
    from icikit.bench.run import main
    trace_dir = tmp_path / "trace"
    rc = main(["--family", "broadcast", "--algorithms", "xla",
               "--sizes", "8", "--runs", "1", "--devices", "2",
               "--profile", str(trace_dir)])
    assert rc == 0
    files = list(trace_dir.rglob("*"))
    assert any(f.is_file() for f in files), "no trace files written"
