"""Oracle tests for the variable-count all-to-all (MPI_Alltoallv
analog): numpy segment reconstruction as the closed-form expectation,
every registered carrier schedule, overflow surfacing."""

import jax.numpy as jnp
import numpy as np
import pytest

from icikit.parallel import ALLTOALL_ALGORITHMS, all_to_all_v
from icikit.utils.mesh import make_mesh, shard_along


def _case(p, L, seed=0, max_seg=None):
    """Random per-pair counts with contiguous MPI-style layout."""
    rng = np.random.default_rng(seed)
    max_seg = max_seg if max_seg is not None else L // p
    counts = rng.integers(0, max_seg + 1, size=(p, p)).astype(np.int32)
    data = np.full((p, L), -1, np.int32)
    for d in range(p):
        off = 0
        for j in range(p):
            c = counts[d, j]
            data[d, off:off + c] = rng.integers(0, 1000, c)
            off += c
    return data, counts


def _expected_rows(data, counts, cap):
    p = counts.shape[0]
    rows = np.full((p, p, cap), np.iinfo(np.int32).max, np.int32)
    for s in range(p):
        off = 0
        for d in range(p):
            c = counts[s, d]
            rows[d, s, :c] = data[s, off:off + c]
            off += c
    return rows


@pytest.mark.parametrize("algorithm", ALLTOALL_ALGORITHMS)
def test_alltoallv_matches_oracle(mesh8, algorithm):
    p, L, cap = 8, 64, 8
    data, counts = _case(p, L, seed=1)
    rows, recv, overflow = all_to_all_v(
        shard_along(jnp.asarray(data), mesh8),
        shard_along(jnp.asarray(counts), mesh8),
        mesh8, capacity=cap, algorithm=algorithm)
    assert int(np.asarray(overflow)[0]) == 0
    np.testing.assert_array_equal(np.asarray(recv), counts.T)
    exp = _expected_rows(data, counts, cap)
    got = np.asarray(rows)
    # only the valid prefix of each row is contractual
    for d in range(p):
        for s in range(p):
            c = counts[s, d]
            np.testing.assert_array_equal(got[d, s, :c], exp[d, s, :c])


def test_alltoallv_overflow_flag(mesh8):
    p, L = 8, 64
    data, counts = _case(p, L, seed=2, max_seg=8)
    counts[3, 5] = 8  # exceeds capacity 4 below
    rows, recv, overflow = all_to_all_v(
        shard_along(jnp.asarray(data), mesh8),
        shard_along(jnp.asarray(counts), mesh8),
        mesh8, capacity=4)
    assert int(np.asarray(overflow)[0]) >= 1
    assert int(np.asarray(recv)[5, 3]) == 4  # clamped, not lied about


def test_alltoallv_default_capacity(mesh8):
    p, L = 8, 32
    data, counts = _case(p, L, seed=3)
    rows, recv, overflow = all_to_all_v(
        shard_along(jnp.asarray(data), mesh8),
        shard_along(jnp.asarray(counts), mesh8), mesh8)
    assert rows.shape == (p, p, L)
    assert int(np.asarray(overflow)[0]) == 0


def test_alltoallv_non_pow2():
    p, L, cap = 6, 36, 6
    mesh = make_mesh(p)
    data, counts = _case(p, L, seed=4)
    rows, recv, _ = all_to_all_v(
        shard_along(jnp.asarray(data), mesh),
        shard_along(jnp.asarray(counts), mesh),
        mesh, capacity=cap, algorithm="wraparound")
    np.testing.assert_array_equal(np.asarray(recv), counts.T)
    exp = _expected_rows(data, counts, cap)
    got = np.asarray(rows)
    for d in range(p):
        for s in range(p):
            c = counts[s, d]
            np.testing.assert_array_equal(got[d, s, :c], exp[d, s, :c])


@pytest.mark.parametrize("algorithm", ["xla", "ring", "recursive_doubling"])
def test_allgatherv_matches_oracle(mesh8, algorithm):
    from icikit.parallel import all_gather_v
    from icikit.parallel.alltoallv import unpack_rows
    p, cap = 8, 10
    rng = np.random.default_rng(10)
    counts = rng.integers(0, cap + 1, p).astype(np.int32)
    data = np.zeros((p, cap), np.int32)
    for d in range(p):
        data[d, :counts[d]] = rng.integers(0, 1000, counts[d])
    rows, all_counts = all_gather_v(
        shard_along(jnp.asarray(data), mesh8),
        shard_along(jnp.asarray(counts), mesh8), mesh8,
        algorithm=algorithm)
    rows, all_counts = np.asarray(rows), np.asarray(all_counts)
    expected = np.concatenate([data[d, :counts[d]] for d in range(p)])
    for d in range(p):
        np.testing.assert_array_equal(all_counts[d], counts)
        flat, total = unpack_rows(jnp.asarray(rows[d]),
                                  jnp.asarray(counts))
        flat = np.asarray(flat)
        got = np.concatenate(
            [flat[s * cap:s * cap + counts[s]] for s in range(p)])
        np.testing.assert_array_equal(got, expected)
        assert int(total) == counts.sum()


def test_allgatherv_validates(mesh8):
    from icikit.parallel import all_gather_v
    x = shard_along(jnp.zeros((8, 4), jnp.int32), mesh8)
    with pytest.raises(ValueError, match="counts must be"):
        all_gather_v(x, jnp.zeros((4,), jnp.int32), mesh8)
    with pytest.raises(ValueError, match="one .* block per device"):
        all_gather_v(jnp.zeros((16, 4), jnp.int32),
                     jnp.zeros((8,), jnp.int32), mesh8)
