"""Flagship schedule options: Ulysses sequence parallelism in the
training step (vs the 1-device program) and MoE decoding (dropless
dispatch vs a per-token routing oracle)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from icikit.models.transformer import (
    TransformerConfig,
    greedy_generate,
    init_params,
    loss_fn,
)
from icikit.models.transformer.model import make_model_mesh

BASE = dict(vocab=61, d_model=32, n_heads=4, d_head=8, d_ff=64,
            n_layers=2, max_seq=32, compute_dtype="float32")


@pytest.mark.parametrize("dp,tp,sp,alg", [(2, 1, 4, "xla"),
                                          (1, 2, 2, "wraparound")])
def test_ulysses_schedule_matches_single_device(dp, tp, sp, alg):
    cfg = TransformerConfig(**BASE, sequence_schedule="ulysses",
                            sp_algorithm=alg)
    rng = np.random.default_rng(0)
    tok = rng.integers(0, cfg.vocab, (4, 32)).astype(np.int32)
    tgt = rng.integers(0, cfg.vocab, (4, 32)).astype(np.int32)

    def run(cfg, dp, tp, sp):
        mesh = make_model_mesh(dp=dp, tp=tp, sp=sp)
        params = init_params(jax.random.key(0), cfg, mesh)
        sh = NamedSharding(mesh, P("dp", "sp"))
        loss, grads = loss_fn(params,
                              jax.device_put(jnp.asarray(tok), sh),
                              jax.device_put(jnp.asarray(tgt), sh),
                              mesh, cfg)
        return float(loss), jax.device_get(grads)

    l1, g1 = run(TransformerConfig(**BASE), 1, 1, 1)
    lp, gp = run(cfg, dp, tp, sp)
    assert l1 == pytest.approx(lp, rel=2e-5)
    for k in g1:
        np.testing.assert_allclose(np.asarray(gp[k]), np.asarray(g1[k]),
                                   atol=5e-5, rtol=5e-4, err_msg=k)


def test_ulysses_head_divisibility_checked():
    cfg = TransformerConfig(**BASE, sequence_schedule="ulysses")
    mesh = make_model_mesh(dp=1, tp=2, sp=4)  # 4/2 = 2 heads, sp=4
    with pytest.raises(ValueError, match="ulysses needs"):
        init_params(jax.random.key(0), cfg, mesh)
    with pytest.raises(ValueError, match="sequence_schedule"):
        init_params(jax.random.key(0),
                    TransformerConfig(**BASE, sequence_schedule="rang"),
                    make_model_mesh(dp=1, tp=1, sp=1))


def _moe_oracle_continue(params, prompt, cfg, n_new):
    """Dropless per-token top-1 routing — what decode's capacity=all
    dispatch computes, written as direct einsums."""
    from icikit.models.attention.dense import dense_attention
    from icikit.models.transformer.model import _rms_norm

    p = {k: jnp.asarray(np.asarray(v)) for k, v in params.items()}
    toks = jnp.asarray(prompt)
    for _ in range(n_new):
        s = toks.shape[1]
        x = p["emb"][toks] + p["pos"][:s]
        for li in range(cfg.n_layers):
            h = _rms_norm(x, p["ln1"][li])
            qkv = jnp.einsum("bsd,dthe->bsthe", h, p["wqkv"][li])
            attn = dense_attention(qkv[:, :, 0], qkv[:, :, 1],
                                   qkv[:, :, 2], causal=True)
            x = x + jnp.einsum("bshe,hed->bsd", attn, p["wo"][li])
            h2 = _rms_norm(x, p["ln2"][li])
            probs = jax.nn.softmax(
                jnp.einsum("bsd,de->bse", h2, p["wr"][li]), axis=-1)
            gate = probs.max(axis=-1)
            expert = probs.argmax(axis=-1)               # (b, s)
            up = jax.nn.gelu(jnp.einsum("bsd,edf->bsef", h2,
                                        p["we1"][li]))
            y = jnp.einsum("bsef,efd->bsed", up, p["we2"][li])
            sel = jnp.take_along_axis(
                y, expert[..., None, None], axis=2)[:, :, 0]
            x = x + sel * gate[..., None]
        x = _rms_norm(x, p["ln_f"])
        logits = jnp.einsum("bd,vd->bv", x[:, -1], p["w_out"])
        nxt = jnp.argmax(logits, axis=-1).astype(toks.dtype)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    return np.asarray(toks)


@pytest.mark.parametrize("dp", [1, 2])
def test_moe_decode_matches_dropless_oracle(dp):
    cfg = TransformerConfig(**BASE, n_experts=4)
    mesh = make_model_mesh(dp=dp, tp=1, sp=1)
    params = init_params(jax.random.key(0), cfg, mesh)
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, cfg.vocab, (2 * dp, 6)).astype(np.int32)
    pd = jax.device_put(jnp.asarray(prompt),
                        NamedSharding(mesh, P("dp", None)))
    got = np.asarray(greedy_generate(params, pd, mesh, cfg, n_new=5))
    want = _moe_oracle_continue(params, prompt, cfg, 5)
    np.testing.assert_array_equal(got, want)
