"""Anomaly watch (`icikit.obs.watch`): windowed detectors over
lock-scoped registry snapshots — SLO burn rate with exact
over-threshold counts, acceptance-drop, watermarks, zero-rate alarms,
`obs.alert` events on the bus, and the per-run health verdict."""

import threading

import pytest

from icikit import obs
from icikit.obs import bus, watch
from icikit.obs.metrics import Registry


def _watch_over(reg, *watchers, interval=0.0):
    w = watch.Watch(*watchers, registry=reg, min_interval_s=interval)
    return w.attach()


# -- histogram over-threshold + race safety -------------------------

def test_track_over_counts_and_snapshots():
    reg = Registry()
    h = reg.histogram("x")
    h.track_over(10.0)
    for v in (5.0, 15.0, 20.0, 9.0):
        h.observe(v)
    s = h.summary()
    assert s["over"] == {"10.0": 2}
    assert s["count"] == 4 and s["sum"] == 49.0
    # snapshot stays strict-JSON serializable
    import json
    json.dumps(reg.snapshot(), allow_nan=False)


def test_summary_race_safe_against_concurrent_observes():
    """The satellite pin: snapshots taken mid-run by the watch must
    never tear (count and sum read under one lock scope — a torn pair
    shows up as a window mean outside the observed value range)."""
    reg = Registry()
    h = reg.histogram("x")
    stop = threading.Event()

    def pound():
        while not stop.is_set():
            h.observe(1.0)

    t = threading.Thread(target=pound)
    t.start()
    try:
        for _ in range(300):
            s = h.summary()
            if s["count"]:
                mean = s["sum"] / s["count"]
                assert mean == pytest.approx(1.0), s
    finally:
        stop.set()
        t.join()


def test_clear_gauges_scopes_arms():
    reg = Registry()
    reg.gauge("serve.occupancy_rows").set(0.9)
    reg.gauge("other.g").set(1.0)
    reg.clear_gauges("serve.")
    snap = reg.snapshot()
    # the stale serve gauge reads as ABSENT, not as a plausible value
    assert "serve.occupancy_rows" not in snap["gauges"]
    assert snap["gauges"]["other.g"] == 1.0


# -- detectors ------------------------------------------------------

def test_slo_burn_rate_fires_over_budget_only():
    reg = Registry()
    w = _watch_over(reg, watch.SloBurnRate("serve.ttft_ms", 100.0,
                                           budget=0.25, min_count=8))
    for _ in range(9):
        reg.histogram("serve.ttft_ms").observe(50.0)
    assert w.poll() == []                       # burn 0
    for i in range(10):
        reg.histogram("serve.ttft_ms").observe(
            200.0 if i < 5 else 50.0)
    alerts = w.poll()                           # burn 0.5 this window
    assert len(alerts) == 1
    a = alerts[0]
    assert a.metric == "serve.ttft_ms" and a.value == 0.5
    # the run-so-far totals never contaminate later windows
    for _ in range(10):
        reg.histogram("serve.ttft_ms").observe(50.0)
    assert w.poll() == []


def test_slo_burn_skips_thin_windows():
    reg = Registry()
    w = _watch_over(reg, watch.SloBurnRate("serve.ttft_ms", 100.0,
                                           budget=0.1, min_count=8))
    for _ in range(7):      # under min_count: one straggler can't alarm
        reg.histogram("serve.ttft_ms").observe(500.0)
    assert w.poll() == []


def test_acceptance_drop_detector():
    reg = Registry()
    w = _watch_over(reg, watch.AcceptanceDrop(floor=0.05,
                                              min_proposed=64))
    reg.counter("serve.spec.draft_proposed").add(100)
    reg.counter("serve.spec.draft_accepted").add(50)
    assert w.poll() == []                       # healthy 0.5
    reg.counter("serve.spec.draft_proposed").add(100)
    reg.counter("serve.spec.draft_accepted").add(1)
    alerts = w.poll()                           # windowed 0.01 < floor
    assert len(alerts) == 1 and alerts[0].value == 0.01
    reg.counter("serve.spec.draft_proposed").add(10)
    assert w.poll() == []                       # thin window skipped


def test_gauge_watermark_skips_unwritten_gauge():
    reg = Registry()
    w = _watch_over(reg,
                    watch.GaugeWatermark("serve.kv.fragmentation",
                                         high=0.9),
                    watch.GaugeWatermark("serve.occupancy_rows",
                                         low=0.1))
    assert w.poll() == []           # never written: skipped, not 0
    reg.gauge("serve.kv.fragmentation").set(0.95)
    reg.gauge("serve.occupancy_rows").set(0.05)
    alerts = w.poll()
    assert {a.metric for a in alerts} == {"serve.kv.fragmentation",
                                          "serve.occupancy_rows"}


def test_rate_alarm_windows_not_totals():
    reg = Registry()
    w = _watch_over(reg, watch.RateAlarm("serve.duplicate_commits"))
    reg.counter("serve.duplicate_commits").add(2)
    alerts = w.poll()
    assert len(alerts) == 1 and alerts[0].severity == "error"
    # no NEW movement: the cumulative total must not re-alarm
    assert w.poll() == []


# -- harness: events, verdict, bench integration --------------------

def test_alerts_land_on_bus_and_in_verdict():
    reg = Registry()
    ring = obs.RingSink()
    w = _watch_over(reg, watch.RateAlarm("serve.integrity_failures"))
    with bus.installed(ring):
        reg.counter("serve.integrity_failures").add(1)
        w.poll()
        verdict = w.verdict()
    evs = ring.of_type("obs.alert")
    assert len(evs) == 1
    assert evs[0]["metric"] == "serve.integrity_failures"
    assert evs[0]["severity"] == "error"
    assert verdict["healthy"] is False and verdict["n_alerts"] == 1
    assert verdict["alerts"][0]["watch"] == \
        "rate[serve.integrity_failures]"
    assert verdict["polls"] == 2    # explicit poll + verdict's final


def test_clean_verdict_healthy():
    reg = Registry()
    w = watch.serve_watch(registry=reg, min_interval_s=0.0).attach()
    reg.histogram("serve.ttft_ms").observe(10.0)
    reg.counter("serve.tokens").add(100)
    reg.gauge("serve.kv.fragmentation").set(0.2)
    v = w.verdict()
    assert v["healthy"] is True and v["n_alerts"] == 0
    assert len(v["watchers"]) >= 8


def test_watch_without_registry_is_inert():
    w = watch.serve_watch().attach()    # no armed registry anywhere
    w.maybe_poll()
    assert w.poll() == []
    assert w.verdict()["polls"] == 0


def test_bench_serve_stamps_health(tmp_path):
    """End-to-end: a tiny continuous bench arm with --watch under an
    armed registry records a healthy verdict in its row."""
    from icikit.bench.serve import make_workload, run_bench
    with obs.session(trace=False):
        recs = run_bench(
            "tiny", rows=2, n_requests=3, rate_rps=100.0,
            prompt_len=8, new_min=2, new_max=4, block_size=4,
            mode="continuous", compute_dtype="float32", watch=True)
    (rec,) = recs
    h = rec["health"]
    assert h["healthy"] is True and h["n_alerts"] == 0
    assert h["polls"] >= 1
    assert rec["tracing"] is False


# -- multi-source watches (the fleet collector's shape, r19) --------

def test_multiwatch_per_source_windows_resist_masking():
    """The reason MultiWatch exists: e0 burns 100% of its SLO budget
    while e1 is clean. Aggregated into ONE registry the combined burn
    fraction (0.5) would sit under a 0.6 budget and the detector would
    stay silent — per-source windows fire on e0 alone, stamped with
    its source."""
    mw = watch.MultiWatch(
        lambda: [watch.SloBurnRate("serve.ttft_ms", 100.0,
                                   budget=0.6, min_count=4)],
        min_interval_s=0.0)
    # interleaved arrival order, as the coordinator's commit path
    # would feed them
    for _ in range(8):
        mw.observe("e0", "serve.ttft_ms", 500.0)   # over SLO
        mw.observe("e1", "serve.ttft_ms", 10.0)    # under
    alerts = mw.poll()
    assert [a.source for a in alerts] == ["e0"]
    assert alerts[0].watch == "slo_burn[serve.ttft_ms]"
    v = mw.verdict()
    assert v["healthy"] is False
    assert v["sources"] == ["e0", "e1"]
    assert v["alerts"][0]["source"] == "e0"


def test_multiwatch_detector_state_not_shared_across_sources():
    """make_watchers is a FACTORY: each source arms its own detector
    instances, so one source's armed thresholds/state never leak into
    a peer's window."""
    built = []

    def make():
        w = watch.SloBurnRate("serve.ttft_ms", 100.0, budget=0.25,
                              min_count=2)
        built.append(w)
        return [w]

    mw = watch.MultiWatch(make, min_interval_s=0.0)
    mw.observe("e0", "serve.ttft_ms", 1.0)
    mw.observe("e1", "serve.ttft_ms", 1.0)
    assert len(built) == 2 and built[0] is not built[1]


def test_straggler_outlier_flags_engine_over_fleet_median():
    det = watch.StragglerOutlier(factor=3.0, min_count=4,
                                 min_sources=2)
    windows = {
        "e0": {"histograms": {"serve.tpot_ms":
                              {"count": 8, "sum": 8.0}}},
        "e1": {"histograms": {"serve.tpot_ms":
                              {"count": 8, "sum": 8.0}}},
        "e2": {"histograms": {"serve.tpot_ms":
                              {"count": 8, "sum": 400.0}}},
    }
    (a,) = det.check_sources(windows)
    assert a.source == "e2" and a.metric == "serve.tpot_ms"
    assert a.value == 50.0 and a.threshold == 3.0  # 3x median 1.0


def test_straggler_outlier_excludes_thin_and_lonely_sources():
    det = watch.StragglerOutlier(factor=3.0, min_count=4,
                                 min_sources=2)
    # a source with too few observations joins neither the median nor
    # the verdict — a cold engine is not a straggler
    windows = {
        "e0": {"histograms": {"serve.tpot_ms":
                              {"count": 8, "sum": 8.0}}},
        "thin": {"histograms": {"serve.tpot_ms":
                                {"count": 2, "sum": 1000.0}}},
    }
    assert det.check_sources(windows) == []     # 1 eligible < 2
    # a 1-engine fleet has no peers to be an outlier against
    assert det.check_sources({"e0": windows["e0"]}) == []


def test_multiwatch_interleaved_multi_engine_stream():
    """Interleaved observations + a cross-source detector in one
    harness: per-source SLO burn fires for the burning engine, the
    straggler fires for the slow one, and both alerts land on the bus
    with their sources."""
    ring = obs.RingSink()
    with bus.installed(ring):
        mw = watch.MultiWatch(
            lambda: [watch.SloBurnRate("serve.tpot_ms", 100.0,
                                       budget=0.5, min_count=4)],
            cross=(watch.StragglerOutlier(factor=3.0, min_count=4),),
            min_interval_s=0.0)
        for _ in range(8):
            mw.observe("e0", "serve.tpot_ms", 1.0)
            mw.observe("e1", "serve.tpot_ms", 2.0)
            mw.observe("e2", "serve.tpot_ms", 500.0)  # burns AND lags
        alerts = mw.poll()
    kinds = sorted((a.watch.split("[")[0], a.source)
                   for a in alerts)
    assert kinds == [("slo_burn", "e2"), ("straggler", "e2")]
    evs = ring.of_type("obs.alert")
    assert sorted(e["source"] for e in evs) == ["e2", "e2"]


def test_multiwatch_maybe_poll_throttles():
    mw = watch.MultiWatch(lambda: [], min_interval_s=3600.0)
    mw.observe("e0", "serve.tpot_ms", 1.0)
    assert mw.maybe_poll() == []        # throttled window
    assert mw.polls == 0
    assert mw.poll() is not None        # forced
    assert mw.polls == 1
