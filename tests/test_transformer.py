"""Transformer flagship tests: sharded-vs-single-device parity and a
training-loop smoke. The parity check plays the role the reference's
payload oracles play for its collectives (``main.cc:436-441``): the
dp x tp x sp result must match the 1-device result bit-for-bit in
structure and to fp tolerance in value."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from icikit.models.transformer import (
    TransformerConfig,
    init_params,
    loss_fn,
    make_train_step,
)
from icikit.models.transformer.model import make_model_mesh

CFG = TransformerConfig(vocab=61, d_model=32, n_heads=4, d_head=8,
                        d_ff=64, n_layers=2, max_seq=32,
                        compute_dtype="float32")


def _batch(cfg, b=8, s=16, seed=0):
    rng = np.random.default_rng(seed)
    tok = rng.integers(0, cfg.vocab, size=(b, s)).astype(np.int32)
    tgt = rng.integers(0, cfg.vocab, size=(b, s)).astype(np.int32)
    return tok, tgt


def _place(mesh, tok, tgt):
    from jax.sharding import NamedSharding, PartitionSpec as P
    sh = NamedSharding(mesh, P("dp", "sp"))
    return (jax.device_put(jnp.asarray(tok), sh),
            jax.device_put(jnp.asarray(tgt), sh))


@pytest.mark.parametrize("dp,tp,sp", [(2, 2, 2), (1, 4, 2), (2, 1, 4),
                                      (8, 1, 1)])
def test_sharded_matches_single_device(dp, tp, sp):
    mesh1 = make_model_mesh(dp=1, tp=1, sp=1)
    meshN = make_model_mesh(dp=dp, tp=tp, sp=sp)
    params1 = init_params(jax.random.key(0), CFG, mesh1)
    paramsN = init_params(jax.random.key(0), CFG, meshN)
    tok, tgt = _batch(CFG)

    loss1, g1 = loss_fn(params1, *_place(mesh1, tok, tgt), mesh1, CFG)
    lossN, gN = loss_fn(paramsN, *_place(meshN, tok, tgt), meshN, CFG)

    np.testing.assert_allclose(float(loss1), float(lossN), rtol=1e-5)
    for k in g1:
        np.testing.assert_allclose(np.asarray(g1[k]), np.asarray(gN[k]),
                                   rtol=2e-4, atol=2e-5, err_msg=k)


def test_loss_matches_dense_oracle():
    """1-device forward against an independent dense-attention oracle
    computed with plain jnp ops (no shard_map)."""
    from icikit.models.attention.dense import dense_attention
    from icikit.models.transformer.model import _rms_norm

    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(1), CFG, mesh)
    tok, tgt = _batch(CFG, seed=3)

    # independent forward
    p = {k: np.asarray(v) for k, v in params.items()}
    x = jnp.asarray(p["emb"])[jnp.asarray(tok)] + jnp.asarray(
        p["pos"][: tok.shape[1]])
    for li in range(CFG.n_layers):
        h = _rms_norm(x, jnp.asarray(p["ln1"][li]))
        qkv = jnp.einsum("bsd,dthe->bsthe", h, jnp.asarray(p["wqkv"][li]))
        attn = dense_attention(qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2],
                               causal=True)
        x = x + jnp.einsum("bshe,hed->bsd", attn, jnp.asarray(p["wo"][li]))
        h2 = _rms_norm(x, jnp.asarray(p["ln2"][li]))
        u = jax.nn.gelu(jnp.einsum("bsd,df->bsf", h2,
                                   jnp.asarray(p["w1"][li])))
        x = x + jnp.einsum("bsf,fd->bsd", u, jnp.asarray(p["w2"][li]))
    x = _rms_norm(x, jnp.asarray(p["ln_f"]))
    logits = jnp.einsum("bsd,vd->bsv", x, jnp.asarray(p["w_out"]))
    logp = jax.nn.log_softmax(logits, axis=-1)
    want = float(-jnp.take_along_axis(
        logp, jnp.asarray(tgt)[..., None], axis=-1).mean())

    got, _ = loss_fn(params, *_place(mesh, tok, tgt), mesh, CFG)
    np.testing.assert_allclose(float(got), want, rtol=1e-5)


def test_train_step_learns():
    mesh = make_model_mesh(dp=2, tp=2, sp=2)
    params = init_params(jax.random.key(2), CFG, mesh)
    tok, tgt = _batch(CFG, seed=4)
    tok_d, tgt_d = _place(mesh, tok, tgt)
    import optax
    optimizer, step = make_train_step(mesh, CFG, optax.adam(1e-2))
    opt_state = optimizer.init(params)
    first = None
    for _ in range(40):
        params, opt_state, loss = step(params, opt_state, tok_d, tgt_d)
        if first is None:
            first = float(loss)
    assert float(loss) < first * 0.5, (first, float(loss))


def test_mesh_validation():
    with pytest.raises(ValueError):
        make_model_mesh(n_devices=8, dp=2, tp=2, sp=1)  # 4 != 8
    with pytest.raises(ValueError):
        make_model_mesh(dp=4, tp=4, sp=4)  # 64 > 8 devices
