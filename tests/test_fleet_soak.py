"""The cross-process ``make chaos`` analogue: p−1-engines-survive.

Four engine PROCESSES (one dedicated prefill, three full) serve a
mixed greedy+sampled trace while two are killed mid-decode
(``die:fleet.engine.die`` inside lease renewal) and one computes
garbage (``corrupt:serve.kv.page`` under ``integrity="pages"`` →
IntegrityError → coordinator quarantine). Exit bar, enforced inside
``tools/fleet_study.soak``: every request completes, every completed
request's tokens are bitwise identical to single-request
``generate``/``sample_generate``, with ≥1 cross-engine KV migration
and the quarantined-defective-engine drill observed in the run.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools"))


@pytest.mark.slow
@pytest.mark.chaos
def test_p_minus_one_engines_survive_soak(tmp_path):
    from fleet_study import soak

    rec = soak(json_path=str(tmp_path / "soak.jsonl"),
               n_requests=10, lease_s=3.0, die_at=(8, 16),
               timeout_s=600.0)
    # the soak asserts its own bars; re-state the headline ones here
    assert rec["completed"] == 10
    assert rec["identity_greedy"]["identity_ok"]
    assert rec["identity_sampled"]["identity_ok"]
    assert rec["engine_states"]["bad2"] == "quarantined"
    assert sum(rec["killed"]) >= 2
    assert rec["reissues"] >= 1
    assert rec["bridge"]["migrations"] >= 1
