"""Differential fuzz over the collective families.

Random (family, algorithm, p, msize, dtype) configurations verified
against the harness's closed-form oracles — deterministic seeds, so a
failure reproduces. Complements the per-family suites by hitting shape
and mesh-size combinations nobody hand-picked (the reference only ever
ran power-of-2 process counts and one dtype)."""

import numpy as np
import pytest

from icikit import chaos
from icikit.bench.harness import _setup
from icikit.utils.mesh import UnsupportedMeshError, make_mesh
from icikit.utils.registry import list_algorithms

FAMILIES = ("allgather", "alltoall", "allreduce", "reducescatter",
            "broadcast", "scatter", "gather", "scan", "reduce")


@pytest.mark.parametrize("seed", range(24))
def test_random_config_verifies(seed):
    rng = np.random.default_rng(seed)
    family = FAMILIES[rng.integers(len(FAMILIES))]
    p = int(rng.choice([2, 3, 4, 5, 6, 8]))
    msize = int(rng.choice([1, 3, 8, 17, 64, 200]))
    dtype = np.dtype([np.int32, np.float32][rng.integers(2)])
    algs = list_algorithms(family)
    algorithm = algs[rng.integers(len(algs))]
    mesh = make_mesh(p)
    run, verify = _setup(family, mesh, "p", msize, dtype)
    try:
        out = run(algorithm)
    except UnsupportedMeshError:
        assert p & (p - 1), (
            f"{family}/{algorithm} rejected a power-of-2 mesh p={p}")
        return
    assert verify(out), (
        f"oracle mismatch: {family}/{algorithm} p={p} msize={msize} "
        f"{dtype}")


# -- checked-mode fuzz (device-side integrity) -----------------------
#
# Same random-config discipline over the checksum-carrying schedules:
# (a) a clean corpus under an ARMED-but-cold corrupt plan must verify
# against the oracle with ZERO detections (the checksum is exact, so
# false positives are a hard failure, not noise), and (b) under a
# scheduled corrupt plan every injected in-schedule flip must be
# detected and retried back to the oracle result.

from icikit.parallel.integrity import CHECKED_FAMILIES  # noqa: E402

# movement-only families shuffle any bit pattern; reductions keep
# dtypes whose numpy oracle matches device arithmetic exactly
_MOVE_DTYPES = (np.int32, np.float32, np.float16, np.int8)
_REDUCE_DTYPES = (np.int32, np.float32)


def _checked_pick(seed):
    rng = np.random.default_rng(10_000 + seed)
    family = CHECKED_FAMILIES[rng.integers(len(CHECKED_FAMILIES))]
    p = int(rng.choice([2, 3, 4, 5, 8]))
    msize = int(rng.choice([1, 3, 8, 17, 64, 200]))
    pool = (_MOVE_DTYPES if family in ("allgather", "alltoall")
            else _REDUCE_DTYPES)
    dtype = np.dtype(pool[rng.integers(len(pool))])
    algs = [a for a in list_algorithms(family) if a != "xla"]
    algorithm = algs[rng.integers(len(algs))]
    return family, algorithm, p, msize, dtype


@pytest.mark.parametrize("seed", range(16))
def test_checked_random_config_no_false_positives(seed):
    from icikit.parallel import integrity

    family, algorithm, p, msize, dtype = _checked_pick(seed)
    mesh = make_mesh(p)
    run, verify = _setup(family, mesh, "p", msize, dtype, checked=True)
    integrity.reset_stats()
    plan = chaos.FaultPlan(rates={"corrupt:collective.*": 0.0})
    try:
        with chaos.inject(plan):
            out = run(algorithm)
    except UnsupportedMeshError:
        assert p & (p - 1), (
            f"{family}/{algorithm} rejected a power-of-2 mesh p={p}")
        return
    assert verify(out), (
        f"oracle mismatch: checked {family}/{algorithm} p={p} "
        f"msize={msize} {dtype}")
    assert integrity.stats()["detected"] == 0, (
        f"false positive: checked {family}/{algorithm} p={p} "
        f"msize={msize} {dtype} flagged a clean run")
    assert plan.log == []


@pytest.mark.parametrize("seed", range(16))
def test_checked_random_config_detects_injected_flip(seed):
    from icikit.parallel import integrity

    family, algorithm, p, msize, dtype = _checked_pick(seed)
    mesh = make_mesh(p)
    run, verify = _setup(family, mesh, "p", msize, dtype, checked=True)
    integrity.reset_stats()
    plan = chaos.FaultPlan(
        seed=seed, schedule={f"corrupt:collective.{family}": (0,)})
    try:
        with chaos.inject(plan):
            out = run(algorithm)
    except UnsupportedMeshError:
        assert p & (p - 1)
        return
    if p == 1:
        return  # no exchanges to corrupt
    assert plan.fired("corrupt", f"collective.{family}") == 1
    st = integrity.stats()
    assert st["detected"] == 1 and st["recoveries"] == 1, (
        f"undetected flip: checked {family}/{algorithm} p={p} "
        f"msize={msize} {dtype}: {st}")
    assert verify(out), "retry did not recover the oracle result"
