"""Differential fuzz over the collective families.

Random (family, algorithm, p, msize, dtype) configurations verified
against the harness's closed-form oracles — deterministic seeds, so a
failure reproduces. Complements the per-family suites by hitting shape
and mesh-size combinations nobody hand-picked (the reference only ever
ran power-of-2 process counts and one dtype)."""

import numpy as np
import pytest

from icikit.bench.harness import _setup
from icikit.utils.mesh import UnsupportedMeshError, make_mesh
from icikit.utils.registry import list_algorithms

FAMILIES = ("allgather", "alltoall", "allreduce", "reducescatter",
            "broadcast", "scatter", "gather", "scan", "reduce")


@pytest.mark.parametrize("seed", range(24))
def test_random_config_verifies(seed):
    rng = np.random.default_rng(seed)
    family = FAMILIES[rng.integers(len(FAMILIES))]
    p = int(rng.choice([2, 3, 4, 5, 6, 8]))
    msize = int(rng.choice([1, 3, 8, 17, 64, 200]))
    dtype = np.dtype([np.int32, np.float32][rng.integers(2)])
    algs = list_algorithms(family)
    algorithm = algs[rng.integers(len(algs))]
    mesh = make_mesh(p)
    run, verify = _setup(family, mesh, "p", msize, dtype)
    try:
        out = run(algorithm)
    except UnsupportedMeshError:
        assert p & (p - 1), (
            f"{family}/{algorithm} rejected a power-of-2 mesh p={p}")
        return
    assert verify(out), (
        f"oracle mismatch: {family}/{algorithm} p={p} msize={msize} "
        f"{dtype}")
