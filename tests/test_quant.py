"""Quantized decode (int8 weights + int8 KV): kernel exactness, scale
edge cases, and the relaxed parity contract.

The parity bar (DECODE.md "Quantized decode"): token identity vs the
fp path is explicitly RELAXED to a measured teacher-forced top-1
agreement — these tests measure it (and verify the relaxation is doing
work: the paths really compute different logits), while *within* the
int8 path the speculative/verify token-identity contract still holds
exactly for every drafter. Kernel-level tests pin the Pallas int8
matvec bit-exactly against the reference dequant matmul.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from icikit.models.transformer import (
    TransformerConfig,
    greedy_generate,
    init_params,
    sample_generate,
    speculative_generate,
)
from icikit.models.transformer.model import make_model_mesh
from icikit.models.transformer.quant import (
    decode_param_specs,
    is_quantized_params,
    measure_top1_agreement,
    quant_param_specs,
    quantize_decode_params,
)
from icikit.ops.quant import (
    dequantize_last,
    qmm,
    quant_matvec,
    quant_matvec_reference,
    quant_matvec_supported,
    quantize_last,
)

CFG = TransformerConfig(vocab=61, d_model=32, n_heads=4, d_head=8,
                        d_ff=64, n_layers=2, max_seq=96,
                        compute_dtype="float32")
QCFG = dataclasses.replace(CFG, decode_quant="int8")


def _mesh(dp=1, tp=1):
    return make_model_mesh(dp=dp, tp=tp, sp=1)


def _prompt(cfg, b=2, s=8, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, cfg.vocab, (b, s)), jnp.int32)


# ------------------------------------------------ quantize / dequant

def test_quantize_roundtrip_error_bound():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(7, 33)) * 10, jnp.float32)
    q, s = quantize_last(x)
    assert q.dtype == jnp.int8 and s.shape == (7,)
    err = np.abs(np.asarray(dequantize_last(q, s)) - np.asarray(x))
    # symmetric round-to-nearest: per-element error <= scale / 2
    assert (err <= np.asarray(s)[:, None] / 2 + 1e-7).all()


def test_quantize_zero_rows_are_exact_and_finite():
    x = jnp.zeros((3, 16), jnp.float32)
    q, s = quantize_last(x)
    assert (np.asarray(q) == 0).all()
    assert (np.asarray(s) == 0).all()
    out = np.asarray(dequantize_last(q, s))
    assert np.isfinite(out).all() and (out == 0).all()
    # mixed: one zero row among live rows must not poison neighbors
    x2 = jnp.asarray(np.stack([np.zeros(16), np.ones(16)]), jnp.float32)
    q2, s2 = quantize_last(x2)
    assert np.asarray(s2)[0] == 0 and np.asarray(s2)[1] > 0
    np.testing.assert_allclose(np.asarray(dequantize_last(q2, s2))[1],
                               np.ones(16), rtol=1e-6)


def test_quantize_saturation_hits_qmax_exactly():
    x = jnp.asarray([[-5.0, 0.0, 5.0, 2.5]], jnp.float32)
    q, s = quantize_last(x)
    qn = np.asarray(q)[0]
    assert qn[0] == -127 and qn[2] == 127          # the channel max
    assert np.asarray(s)[0] == pytest.approx(5.0 / 127.0)


def test_quantize_rejects_unknown_dtype():
    with pytest.raises(ValueError, match="unknown quant dtype"):
        quantize_last(jnp.ones((2, 4)), qdtype="int3")


def test_quantize_fp8_uses_float_rounding():
    """The fp8 plumbing must NOT integer-round: values below scale/2
    survive (fp8's value grid is not the integers), and dequant error
    stays within fp8 e4m3 relative precision (~2^-3 of the value) —
    the broken integer form collapsed 0.001 to exact zero."""
    from icikit.ops.quant import QDTYPES
    if QDTYPES["fp8_e4m3"][0] is None:
        pytest.skip("no fp8_e4m3 in this jax build")
    x = jnp.asarray([[0.001, 0.002, 0.003, 1.0]], jnp.float32)
    q, s = quantize_last(x, qdtype="fp8_e4m3")
    deq = np.asarray(dequantize_last(q, s))[0]
    assert deq[0] != 0.0                       # sub-half-scale survives
    np.testing.assert_allclose(deq, np.asarray(x)[0], rtol=0.13)


# ------------------------------------------------------ the kernel

def test_quant_matvec_exact_vs_reference():
    """Kernel-level exact-logit bar: the Pallas int8 matvec must equal
    the reference dequant matmul BITWISE (fp32 accumulation both)."""
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(4, 256)), jnp.float32)
    q, s = quantize_last(jnp.asarray(rng.normal(size=(512, 256)),
                                     jnp.float32))
    out = np.asarray(quant_matvec(x, q, s))
    ref = np.asarray(quant_matvec_reference(x, q, s))
    np.testing.assert_array_equal(out, ref)
    # and within quantization error of the UNfactored dequant matmul
    deq = np.asarray(q, np.float32) * np.asarray(s)[:, None]
    full = np.asarray(x) @ deq.T
    np.testing.assert_allclose(out, full, rtol=1e-5, atol=1e-5)


def test_quant_matvec_gate_rejects_ragged():
    # ragged contraction dim (not lane-exact) and untileable channel
    # count must be rejected by the gate, loudly by the kernel
    assert not quant_matvec_supported(4, 512, 100)   # k % 128 != 0
    assert not quant_matvec_supported(4, 130, 256)   # n untileable
    assert quant_matvec_supported(4, 512, 256)
    x = jnp.ones((4, 100), jnp.float32)
    q, s = quantize_last(jnp.ones((512, 100), jnp.float32))
    with pytest.raises(ValueError, match="quant_matvec unsupported"):
        quant_matvec(x, q, s)


def test_qmm_xla_fallback_matches_kernel_math():
    """The ragged-shape XLA fallback computes the same factored math:
    on a kernel-supported shape the two impls agree to fp32 tolerance,
    and impl='pallas' on an unsupported shape fails loudly."""
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(3, 256)), jnp.float32)
    q, s = quantize_last(jnp.asarray(rng.normal(size=(256, 256)),
                                     jnp.float32))
    a = np.asarray(qmm(x, q, s, impl="pallas"))
    b = np.asarray(qmm(x, q, s, impl="xla"))
    np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)
    xq, qq, sq = (jnp.ones((3, 20), jnp.float32),) + quantize_last(
        jnp.ones((7, 20), jnp.float32))
    with pytest.raises(ValueError, match="unsupported"):
        qmm(xq, qq, sq, impl="pallas")
    assert np.asarray(qmm(xq, qq, sq, impl="xla")).shape == (3, 7)


# -------------------------------------------------- pytree plumbing

def test_quantize_decode_params_layouts_and_specs():
    mesh = _mesh()
    params = init_params(jax.random.key(0), CFG, mesh)
    qp = quantize_decode_params(params, QCFG, mesh)
    assert is_quantized_params(qp)
    assert qp["w_out"].dtype == jnp.int8
    assert qp["w_out_s"].shape == (CFG.vocab,)
    L, D, H, Dh, F = (CFG.n_layers, CFG.d_model, CFG.n_heads,
                      CFG.d_head, CFG.d_ff)
    assert qp["wqkv"].shape == (L, 3, H, Dh, D)      # contraction last
    assert qp["wo"].shape == (L, D, H, Dh)
    assert qp["w1"].shape == (L, F, D)
    assert qp["w2"].shape == (L, D, F)
    # specs cover exactly the quantized tree, and idempotence holds
    assert set(quant_param_specs(QCFG)) == set(qp)
    assert quantize_decode_params(qp, QCFG, mesh) is qp
    assert decode_param_specs(CFG).keys() == params.keys()


def test_cfg_validation():
    with pytest.raises(ValueError, match="decode_quant"):
        greedy_generate({}, _prompt(CFG), _mesh(),
                        dataclasses.replace(CFG, decode_quant="fp4"), 4)
    with pytest.raises(ValueError, match="dense FFNs only"):
        dataclasses.replace(  # construction-time gate via param_specs
            CFG, decode_quant="int8", n_experts=2)
        from icikit.models.transformer.model import _check_cfg
        _check_cfg(dataclasses.replace(CFG, decode_quant="int8",
                                       n_experts=2))


# ------------------------------------------- generate-level parity

def test_int8_generate_runs_and_relaxation_is_measured():
    """The relaxed parity contract, tested not assumed: the int8 path
    computes genuinely different logits (the comparison is not
    vacuous), tokens MAY diverge from fp, and the measured
    teacher-forced top-1 agreement is the metric that bounds it."""
    mesh = _mesh()
    params = init_params(jax.random.key(0), CFG, mesh)
    prompt = _prompt(CFG)
    y = greedy_generate(params, prompt, mesh, CFG, 24)
    r = measure_top1_agreement(params, y, mesh, QCFG, prompt.shape[1])
    assert r["max_logit_abs_diff"] > 0          # quantization engaged
    assert r["n_positions"] > 0
    # random-init toy: near-uniform logits are the worst case for an
    # argmax metric, and agreement must still be high; the >= 0.999
    # bar is measured on the TRAINED toy (tools/quant_decode_study.py,
    # recorded in DECODE.md round 10 + the slow test below)
    assert r["top1_agreement"] >= 0.9
    # int8 tokens are a valid continuation of the same prompt
    yq = greedy_generate(params, prompt, mesh, QCFG, 24)
    assert np.asarray(yq).shape == np.asarray(y).shape
    np.testing.assert_array_equal(np.asarray(yq)[:, :prompt.shape[1]],
                                  np.asarray(prompt))
    # and an empty scoring region fails LOUDLY, never as NaN agreement
    with pytest.raises(ValueError, match="no scorable positions"):
        measure_top1_agreement(params, y[:, :prompt.shape[1] + 1],
                               mesh, QCFG, prompt.shape[1])


@pytest.mark.parametrize("dp,tp", [(1, 1), (2, 4)])
def test_int8_generate_mesh_invariance(dp, tp):
    cfg = dataclasses.replace(QCFG, vocab=64, vocab_parallel=tp > 1)
    mesh1 = _mesh()
    base_cfg = dataclasses.replace(cfg, vocab_parallel=False)
    params = init_params(jax.random.key(1),
                         dataclasses.replace(base_cfg,
                                             decode_quant="none"),
                         mesh1)
    prompt = _prompt(cfg)
    want = np.asarray(greedy_generate(params, prompt, mesh1, base_cfg,
                                      12))
    mesh = _mesh(dp=dp, tp=tp)
    params_n = init_params(jax.random.key(1),
                           dataclasses.replace(cfg,
                                               decode_quant="none"),
                           mesh)
    got = np.asarray(greedy_generate(params_n, prompt, mesh, cfg, 12))
    np.testing.assert_array_equal(got, want)


def test_int8_sample_generate_reproducible():
    mesh = _mesh()
    params = init_params(jax.random.key(0), CFG, mesh)
    prompt = _prompt(CFG)
    a = sample_generate(params, prompt, mesh, QCFG, 12,
                        jax.random.key(7), temperature=0.8, top_k=8)
    b = sample_generate(params, prompt, mesh, QCFG, 12,
                        jax.random.key(7), temperature=0.8, top_k=8)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_prequantized_params_give_identical_tokens():
    """Hoisting the conversion (the engine/bench pattern) must change
    nothing: generate with fp params quantized on the fly == generate
    with an explicitly pre-quantized pytree."""
    mesh = _mesh()
    params = init_params(jax.random.key(0), CFG, mesh)
    prompt = _prompt(CFG)
    a = np.asarray(greedy_generate(params, prompt, mesh, QCFG, 16))
    qp = quantize_decode_params(params, QCFG, mesh)
    b = np.asarray(greedy_generate(qp, prompt, mesh, QCFG, 16))
    np.testing.assert_array_equal(a, b)


# --------------------------------------- speculative token identity

@pytest.mark.parametrize("drafter", ["shared", "ngram"])
def test_speculative_int8_token_identity(drafter):
    """WITHIN the int8 path the verify/accept contract is exact: every
    committed token is the int8 model's argmax, for any drafter."""
    cfg = dataclasses.replace(QCFG, n_layers=4)
    mesh = _mesh()
    params = init_params(jax.random.key(0),
                         dataclasses.replace(cfg, decode_quant="none"),
                         mesh)
    prompt = _prompt(cfg)
    base = np.asarray(greedy_generate(params, prompt, mesh, cfg, 16))
    out = np.asarray(speculative_generate(params, prompt, mesh, cfg,
                                          16, k=3, draft_layers=2,
                                          drafter=drafter))
    np.testing.assert_array_equal(out, base)


def test_speculative_int8_trained_drafter_identity():
    cfg = dataclasses.replace(QCFG, n_layers=4, draft_head=True,
                              draft_layers=1)
    mesh = _mesh()
    params = init_params(jax.random.key(0),
                         dataclasses.replace(cfg, decode_quant="none"),
                         mesh)
    prompt = _prompt(cfg)
    base = np.asarray(greedy_generate(params, prompt, mesh, cfg, 16))
    out = np.asarray(speculative_generate(params, prompt, mesh, cfg,
                                          16, k=3, drafter="trained"))
    np.testing.assert_array_equal(out, base)


# ------------------------------------------------ fused decode step

def test_fused_decode_step_q8_token_identity():
    """The int8 fused Pallas step (in-kernel dequant) is token-
    identical to the unfused int8 formulation — with and without
    rope (interpret mode on CPU, the decode_step test discipline)."""
    from icikit.bench.train import PRESETS
    for pos in ("learned", "rope"):
        cfg = TransformerConfig(**PRESETS["tiny128"],
                                compute_dtype="float32",
                                pos_encoding=pos, decode_quant="int8")
        mesh = _mesh()
        params = init_params(
            jax.random.key(2),
            dataclasses.replace(cfg, decode_quant="none"), mesh)
        prompt = _prompt(cfg, seed=3)
        unfused = np.asarray(greedy_generate(params, prompt, mesh, cfg,
                                             10))
        fused = np.asarray(greedy_generate(
            params, prompt, mesh,
            dataclasses.replace(cfg, decode_step="fused"), 10))
        np.testing.assert_array_equal(fused, unfused)


def test_fused_decode_step_q8_caches_stay_int8():
    """The int8 path's cache carries are int8 + fp32 scales — no
    cache-shaped fp tensor is allocated (the make-check lint's
    invariant, asserted here at the prefill boundary)."""
    from jax.sharding import PartitionSpec as P

    from icikit.models.transformer.decode import _DecodeCtx, _prefill
    from icikit.parallel.shmap import wrap_program
    cfg = QCFG
    mesh = _mesh()
    params = init_params(jax.random.key(0), CFG, mesh)
    qp = quantize_decode_params(params, QCFG, mesh)
    ctx = _DecodeCtx(cfg, mesh)
    cspec = P(None, "dp", None, None, None)
    prog = wrap_program(
        lambda p, t: _prefill(ctx, p, t, 8, 24, fused=False)[1],
        mesh, (decode_param_specs(cfg), P("dp", None)),
        (cspec, cspec, P(None, "dp", None, None),
         P(None, "dp", None, None)))
    ks, vs, kss, vss = jax.eval_shape(prog, qp, _prompt(cfg))
    assert ks.dtype == jnp.int8 and vs.dtype == jnp.int8
    assert kss.dtype == jnp.float32 and vss.dtype == jnp.float32


# ---------------------------------------------------- trained bar

@pytest.mark.slow
def test_trained_toy_clears_top1_agreement_bar():
    """The measured >= 0.999 bar on a genuinely trained, CONFIDENT
    model — the regime greedy decode serves (the r10 study's
    deterministic-corpus toy; validated 1.0 over 3072 positions with
    max logit deviation ~0.22). On the entropy-limited branch-4 r8
    teacher the same metric reads ~0.97 with every disagreement at an
    fp top-2 margin < 0.22 (near-ties where the fp path itself is
    unstable) — both regimes are recorded by
    tools/quant_decode_study.py in DECODE.md round 10."""
    import optax

    from icikit.models.transformer.model import make_train_step
    from icikit.models.transformer.train import make_markov_sampler

    cfg = TransformerConfig(vocab=16, d_model=64, n_heads=2, d_head=32,
                            d_ff=256, n_layers=4, max_seq=160,
                            compute_dtype="float32")
    mesh = _mesh()
    qcfg = dataclasses.replace(cfg, decode_quant="int8")
    sampler = make_markov_sampler(cfg.vocab, seed=0, branch=1)
    params = init_params(jax.random.key(0), cfg, mesh)
    _, step = make_train_step(mesh, cfg, optax.adam(3e-3))
    st = optax.adam(3e-3).init(params)
    for s in range(1500):
        chunk = sampler(s, 16, 64)
        params, st, _ = step(params, st, jnp.asarray(chunk[:, :-1]),
                             jnp.asarray(chunk[:, 1:]))
    prompts = jnp.asarray(sampler(9, 32, 64)[:, :32], jnp.int32)
    y = greedy_generate(params, prompts, mesh, cfg, 96)
    r = measure_top1_agreement(params, y, mesh, qcfg, 32)
    assert r["max_logit_abs_diff"] > 0
    assert r["top1_agreement"] >= 0.999, r
