"""Tests for the runtime core: mesh, registry, timing, RNG invariance."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from icikit.utils.mesh import ilog2, is_pow2, make_mesh, mesh_axis_size, shard_along
from icikit.utils.prandom import odd_dist_warp, uniform_block, uniform_global
from icikit.utils.registry import get_algorithm, list_algorithms, register_algorithm
from icikit.utils.timing import Stopwatch, timeit


def test_pow2_helpers():
    assert [is_pow2(n) for n in [1, 2, 3, 4, 6, 8]] == \
        [True, True, False, True, False, True]
    assert ilog2(8) == 3
    with pytest.raises(ValueError):
        ilog2(6)


def test_make_mesh(mesh8):
    assert mesh_axis_size(mesh8) == 8
    with pytest.raises(ValueError):
        make_mesh(1024)


def test_registry():
    @register_algorithm("_testfam", "a")
    def impl_a():
        return "a"

    assert get_algorithm("_testfam", "a") is impl_a
    assert list_algorithms("_testfam") == ["a"]
    with pytest.raises(KeyError, match="unknown algorithm"):
        get_algorithm("_testfam", "missing")
    with pytest.raises(ValueError, match="duplicate"):
        register_algorithm("_testfam", "a")(impl_a)


def test_stopwatch_resets_on_read():
    watch = Stopwatch()
    t1 = watch()
    t2 = watch()
    assert t1 >= 0 and t2 >= 0


def test_timeit_reports_mean():
    res = timeit(lambda x: x + 1, jnp.ones(8), runs=3, warmup=1)
    assert res.runs == 3
    assert res.total_s == pytest.approx(sum(res.per_run_s))
    assert res.mean_s == pytest.approx(res.total_s / 3)


def _synthetic_window_fn(readings):
    """A window_fn yielding a scripted sequence of per-run readings —
    the synthetic noisy timer the escalation logic is tested against."""
    it = iter(readings)

    def window_fn():
        return next(it), 1

    return window_fn


def test_windows_stable_session_no_escalation():
    from icikit.utils.timing import _collect_windows
    pers, dropped, total, escalated, degraded = _collect_windows(
        _synthetic_window_fn([1.00, 1.02, 0.99, 5.0, 5.0, 5.0]),
        windows=3, floor_s=None, escalate_ratio=0.15, max_windows=9)
    assert pers == [1.00, 1.02, 0.99]      # stops at 3: never sees the 5s
    assert not escalated and not degraded
    assert total == 3 and dropped == []


def test_windows_escalation_converges_on_dominant_mode():
    """BENCH_r04's failure shape: one depressed-tail window inside the
    initial three skews the median; escalation keeps sampling until
    the dominant session mode wins the median."""
    from icikit.utils.timing import _collect_windows, _median
    # initial 3: two fast + one 50%-slow tail -> spread 0.5 > 0.15
    seq = [1.0, 1.02, 1.5, 1.01, 0.99, 1.03, 1.0, 1.02, 0.98]
    pers, _, total, escalated, degraded = _collect_windows(
        _synthetic_window_fn(seq), windows=3, floor_s=None,
        escalate_ratio=0.15, max_windows=9)
    assert escalated
    assert len(pers) == 6                  # one escalation round ran
    assert _median(pers) == pytest.approx(1.01, abs=0.02)
    # the lone 1.5 outlier is trimmed from the convergence judgment:
    # the median has converged on the dominant mode, so the session is
    # escalated-but-recovered, NOT degraded
    assert not degraded


def test_windows_spread_within_threshold_not_degraded():
    from icikit.utils.timing import _collect_windows
    pers, _, _, escalated, degraded = _collect_windows(
        _synthetic_window_fn([1.0, 1.1, 1.05]), windows=3,
        floor_s=None, escalate_ratio=0.15, max_windows=9)
    assert not escalated and not degraded  # 10% spread: within bounds


def test_windows_escalation_bounded_by_max_windows():
    from icikit.utils.timing import _collect_windows
    # alternating bimodal session never converges: must stop at
    # max_windows and flag degraded
    seq = [1.0, 2.0] * 20
    pers, _, _, escalated, degraded = _collect_windows(
        _synthetic_window_fn(seq), windows=3, floor_s=None,
        escalate_ratio=0.15, max_windows=9)
    assert escalated and degraded
    assert len(pers) == 9                  # hard bound respected


def test_windows_initial_trigger_never_trims():
    """A lone severe outlier among >=5 INITIAL windows must fire
    escalation — the outlier trim applies only to the post-escalation
    convergence judgment (review finding r5)."""
    from icikit.utils.timing import _collect_windows
    seq = [1.0, 1.01, 1.0, 1.02, 1.5] + [1.0, 1.01, 1.02, 1.0, 1.01]
    pers, _, _, escalated, degraded = _collect_windows(
        _synthetic_window_fn(seq), windows=5, floor_s=None,
        escalate_ratio=0.15, max_windows=15)
    assert escalated            # the untrimmed trigger fired
    assert len(pers) == 10      # one escalation round, then converged
    assert not degraded         # trimmed judgment: dominant mode won


def test_windows_floor_discards_interact_with_escalation():
    from icikit.utils.timing import _collect_windows
    # corrupted-fast readings below the floor are dropped, not kept,
    # and do not count toward the escalation budget's kept windows
    seq = [0.001, 1.0, 0.001, 1.02, 1.01, 5.0]
    pers, dropped, _, escalated, _ = _collect_windows(
        _synthetic_window_fn(seq), windows=3, floor_s=0.5,
        escalate_ratio=0.15, max_windows=9)
    assert pers == [1.0, 1.02, 1.01]
    assert dropped == [0.001, 0.001]
    assert not escalated


def test_timeit_windows_stamps_session_quality():
    from icikit.utils.timing import timeit_windows
    res = timeit_windows(lambda x: x + 1, (jnp.ones(64),),
                         lambda a, out: (out,), windows=2, runs=1)
    q = res.session_quality()
    assert {"spread_ratio", "escalated", "degraded"} <= set(q)
    assert res.windows >= 2
    assert q["spread_ratio"] == pytest.approx(res.spread_ratio, abs=1e-3)


def test_session_canary_stamped_and_cached(monkeypatch):
    """The fixed canary kernel (VERDICT r5 weak #3): measured once per
    process, stamped into session_quality so cross-round headline
    walks are attributable to fabric mood vs regression."""
    from icikit.utils import timing
    from icikit.utils.timing import session_canary, timeit_windows

    monkeypatch.setattr(timing, "_canary_cache", None)
    c = session_canary()
    assert c is not None and c["canary_gbs"] > 0 and c["canary_ms"] > 0
    # cached: the second call returns the same object, no re-measure
    assert session_canary() is timing._canary_cache
    res = timeit_windows(lambda x: x + 1, (jnp.ones(64),),
                         lambda a, out: (out,), windows=2, runs=1)
    q = res.session_quality()
    assert q["canary_gbs"] == c["canary_gbs"]
    # and the kill switch for hosts where even 8 MiB matters
    monkeypatch.setenv("ICIKIT_CANARY", "0")
    assert session_canary() is None
    monkeypatch.setenv("ICIKIT_CANARY", "1")
    assert session_canary() is not None  # cache survives the toggle


def test_rng_partition_invariance(mesh8):
    """The reference's seed-chain guarantees the same global sequence for
    any p (psort.cc:575-581); here the same invariant holds by
    construction — assert it for the sharded-generation path."""
    key = jax.random.key(42)
    n = 1 << 12
    ref = np.asarray(uniform_global(key, n))
    sharded = shard_along(uniform_global(key, n).reshape(8, -1), mesh8)
    np.testing.assert_array_equal(np.asarray(sharded).ravel(), ref)

    # block generator is self-consistent across partitionings
    a = np.concatenate([np.asarray(uniform_block(key, n, i * (n // 4), n // 4))
                        for i in range(4)])
    b = np.concatenate([np.asarray(uniform_block(key, n, i * (n // 8), n // 8))
                        for i in range(8)])
    np.testing.assert_array_equal(a, b)


def test_odd_dist_warp_matches_reference_formula():
    """val = (val^(1 + 3*i/n))^2, psort.cc:600-609."""
    n = 100
    vals = np.linspace(0.01, 0.99, n).astype(np.float32)
    warped = np.asarray(odd_dist_warp(jnp.asarray(vals)))
    i = np.arange(n, dtype=np.float32)
    expected = (vals ** (1.0 + 3.0 * i / n)) ** 2
    np.testing.assert_allclose(warped, expected, rtol=1e-5)
    # block path agrees with global path
    blk = np.asarray(odd_dist_warp(jnp.asarray(vals[40:60]), 40, n))
    np.testing.assert_allclose(blk, expected[40:60], rtol=1e-5)


def test_odd_dist_skews_low():
    """The warp pushes mass toward 0 increasingly with position —
    the load-imbalance stressor for the sorting study."""
    key = jax.random.key(0)
    vals = np.asarray(uniform_global(key, 1 << 14, odd_dist=True))
    assert (vals < 0.5).mean() > 0.6
