"""Tests for the runtime core: mesh, registry, timing, RNG invariance."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from icikit.utils.mesh import ilog2, is_pow2, make_mesh, mesh_axis_size, shard_along
from icikit.utils.prandom import odd_dist_warp, uniform_block, uniform_global
from icikit.utils.registry import get_algorithm, list_algorithms, register_algorithm
from icikit.utils.timing import Stopwatch, timeit


def test_pow2_helpers():
    assert [is_pow2(n) for n in [1, 2, 3, 4, 6, 8]] == \
        [True, True, False, True, False, True]
    assert ilog2(8) == 3
    with pytest.raises(ValueError):
        ilog2(6)


def test_make_mesh(mesh8):
    assert mesh_axis_size(mesh8) == 8
    with pytest.raises(ValueError):
        make_mesh(1024)


def test_registry():
    @register_algorithm("_testfam", "a")
    def impl_a():
        return "a"

    assert get_algorithm("_testfam", "a") is impl_a
    assert list_algorithms("_testfam") == ["a"]
    with pytest.raises(KeyError, match="unknown algorithm"):
        get_algorithm("_testfam", "missing")
    with pytest.raises(ValueError, match="duplicate"):
        register_algorithm("_testfam", "a")(impl_a)


def test_stopwatch_resets_on_read():
    watch = Stopwatch()
    t1 = watch()
    t2 = watch()
    assert t1 >= 0 and t2 >= 0


def test_timeit_reports_mean():
    res = timeit(lambda x: x + 1, jnp.ones(8), runs=3, warmup=1)
    assert res.runs == 3
    assert res.total_s == pytest.approx(sum(res.per_run_s))
    assert res.mean_s == pytest.approx(res.total_s / 3)


def test_rng_partition_invariance(mesh8):
    """The reference's seed-chain guarantees the same global sequence for
    any p (psort.cc:575-581); here the same invariant holds by
    construction — assert it for the sharded-generation path."""
    key = jax.random.key(42)
    n = 1 << 12
    ref = np.asarray(uniform_global(key, n))
    sharded = shard_along(uniform_global(key, n).reshape(8, -1), mesh8)
    np.testing.assert_array_equal(np.asarray(sharded).ravel(), ref)

    # block generator is self-consistent across partitionings
    a = np.concatenate([np.asarray(uniform_block(key, n, i * (n // 4), n // 4))
                        for i in range(4)])
    b = np.concatenate([np.asarray(uniform_block(key, n, i * (n // 8), n // 8))
                        for i in range(8)])
    np.testing.assert_array_equal(a, b)


def test_odd_dist_warp_matches_reference_formula():
    """val = (val^(1 + 3*i/n))^2, psort.cc:600-609."""
    n = 100
    vals = np.linspace(0.01, 0.99, n).astype(np.float32)
    warped = np.asarray(odd_dist_warp(jnp.asarray(vals)))
    i = np.arange(n, dtype=np.float32)
    expected = (vals ** (1.0 + 3.0 * i / n)) ** 2
    np.testing.assert_allclose(warped, expected, rtol=1e-5)
    # block path agrees with global path
    blk = np.asarray(odd_dist_warp(jnp.asarray(vals[40:60]), 40, n))
    np.testing.assert_allclose(blk, expected[40:60], rtol=1e-5)


def test_odd_dist_skews_low():
    """The warp pushes mass toward 0 increasingly with position —
    the load-imbalance stressor for the sorting study."""
    key = jax.random.key(0)
    vals = np.asarray(uniform_global(key, 1 << 14, odd_dist=True))
    assert (vals < 0.5).mean() > 0.6
