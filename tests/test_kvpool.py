"""KV-block allocator property/fuzz suite + pool integrity unit tests.

The allocator is pure host metadata, so the fuzz loop can hammer
thousands of random alloc/extend/free interleavings and check the
invariants that make paged attention safe:

- live block tables never alias (a block serves exactly one owner);
- the free list conserves capacity (free + live == capacity, no block
  minted or leaked, ever);
- exhaustion raises :class:`PoolExhausted` cleanly — all-or-nothing,
  allocator state unchanged;
- ``free`` is idempotent and block 0 (the trash block) is never
  handed out.
"""

import numpy as np
import pytest

from icikit.serve.kvpool import (
    BlockAllocator,
    PoolExhausted,
    block_hashes,
)


def _check_invariants(a: BlockAllocator):
    live = []
    for o in a.owners():
        live.extend(a.table(o))
    assert len(live) == len(set(live)), "live blocks alias"
    assert all(1 <= b <= a.capacity for b in live), \
        "allocated id outside [1, capacity] (trash block 0 leaked?)"
    assert a.n_free + len(live) == a.capacity, "capacity not conserved"


def test_alloc_free_roundtrip():
    a = BlockAllocator(8, 4)
    t = a.alloc("r0", 3)
    assert len(t) == 3 and a.table("r0") == t
    assert a.n_free == 5
    assert a.free("r0") == 3
    assert a.n_free == 8
    assert a.free("r0") == 0          # idempotent
    assert a.n_free == 8


def test_ensure_grows_to_token_count():
    a = BlockAllocator(8, 4)
    assert len(a.ensure("r", 1)) == 1     # 1 token -> 1 block
    assert len(a.ensure("r", 4)) == 0     # still covered
    assert len(a.ensure("r", 5)) == 1     # crosses the boundary
    assert len(a.ensure("r", 17)) == 3    # ceil(17/4) = 5 total
    assert len(a.table("r")) == 5


def test_exhaustion_is_all_or_nothing():
    a = BlockAllocator(4, 4)
    a.alloc("r0", 3)
    before_free = a.n_free
    before_table = a.table("r0")
    with pytest.raises(PoolExhausted) as ei:
        a.alloc("r1", 2)
    assert ei.value.requested == 2 and ei.value.free == 1
    assert a.n_free == before_free          # nothing handed out
    assert a.table("r0") == before_table
    assert a.table("r1") == ()
    _check_invariants(a)


def test_fuzz_interleavings_never_alias():
    """Random alloc/ensure/free streams across many owners: the three
    safety invariants hold at every step, and a drained allocator
    always returns to full capacity."""
    rng = np.random.default_rng(7)
    for trial in range(30):
        cap = int(rng.integers(4, 40))
        bs = int(rng.integers(1, 9))
        a = BlockAllocator(cap, bs)
        owners = [f"r{i}" for i in range(int(rng.integers(2, 9)))]
        for _ in range(200):
            op = rng.integers(0, 3)
            o = owners[int(rng.integers(0, len(owners)))]
            try:
                if op == 0:
                    a.alloc(o, int(rng.integers(0, 5)))
                elif op == 1:
                    a.ensure(o, int(rng.integers(1, cap * bs + 1)))
                else:
                    a.free(o)
            except PoolExhausted as e:
                assert e.requested > e.free    # raised honestly
            _check_invariants(a)
        for o in owners:
            a.free(o)
        assert a.n_free == cap


def test_kvpool_seal_verify_detects_poke():
    """The integrity path end-to-end at pool level: seal a page,
    corrupt it via poke_page, verify flags exactly that block — the
    mechanism behind the serve.kv.page containment drill."""
    import jax

    from icikit.models.transformer import TransformerConfig, init_params
    from icikit.models.transformer.model import make_model_mesh
    from icikit.serve.kvpool import KVPool

    cfg = TransformerConfig(vocab=31, d_model=16, n_heads=2, d_head=8,
                            d_ff=32, n_layers=2, max_seq=32,
                            compute_dtype="float32")
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    init_params(jax.random.key(0), cfg, mesh)  # exercise cfg checks
    pool = KVPool(cfg, mesh, n_blocks=8, block_size=4)
    table = pool.allocators[0].alloc("req", 2)
    # write something nonzero into both pages, then seal them
    data = np.arange(4 * 2 * 8, dtype=np.float32).reshape(4, 2, 8)
    for page in table:
        pool.poke_page(0, page, 0, data + page)
        pool.seal(0, page)
    assert pool.verify("req", 0) == []
    flipped = np.array(data)
    flipped[0, 0, 0] += 1.0
    pool.poke_page(0, table[1], 0, flipped + 1)
    assert pool.verify("req", 0) == [1]
    # seals are content-keyed: releasing the owner frees the pages
    # (unindexed) and drops their digests with them
    pool.release("req", 0)
    assert pool.verify("req", 0) == []


def test_kvpool_occupancy_and_fragmentation():
    import jax

    from icikit.models.transformer import TransformerConfig
    from icikit.models.transformer.model import make_model_mesh
    from icikit.serve.kvpool import KVPool

    del jax
    cfg = TransformerConfig(vocab=31, d_model=16, n_heads=2, d_head=8,
                            d_ff=32, n_layers=1, max_seq=32,
                            compute_dtype="float32")
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    pool = KVPool(cfg, mesh, n_blocks=8, block_size=4)
    assert pool.occupancy() == 0.0
    pool.ensure("a", 0, 6)      # 2 blocks for 6 tokens
    assert pool.occupancy() == pytest.approx(2 / 8)
    # 6 of 8 allocated slots used -> fragmentation 0.25
    assert pool.fragmentation({("a", 0): 6}) == pytest.approx(0.25)
    pool.free("a", 0)
    assert pool.occupancy() == 0.0


def _tiny_cfg():
    from icikit.models.transformer import TransformerConfig
    return TransformerConfig(vocab=31, d_model=16, n_heads=2, d_head=8,
                             d_ff=32, n_layers=2, max_seq=32,
                             compute_dtype="float32")


@pytest.mark.parametrize("quant", ["int8", "mixed"])
def test_kvpool_int8_arenas_and_allocator_properties(quant):
    """int8/mixed pools: arena dtypes + the allocator property run on
    the quantized pool (the allocator is arena-independent by design,
    and this pins that the int8 wiring kept it so)."""
    import jax.numpy as jnp

    from icikit.models.transformer.model import make_model_mesh
    from icikit.serve.kvpool import KVPool

    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    pool = KVPool(_tiny_cfg(), mesh, n_blocks=8, block_size=4,
                  quant=quant)
    assert pool.qkc[0].dtype == jnp.int8
    assert pool.ksc[0].dtype == jnp.float32
    assert pool.ksc[0].shape == pool.qkc[0].shape[:-1]
    if quant == "int8":
        assert pool.kc is None          # no fp arena on the int8 path
        assert set(pool.buffers()) == {"qkc", "qvc", "ksc", "vsc"}
    else:
        assert pool.kc is not None
        assert set(pool.buffers()) == {"kc", "vc", "qkc", "qvc",
                                       "ksc", "vsc"}
    rng = np.random.default_rng(13)
    a = pool.allocators[0]
    owners = [f"r{i}" for i in range(5)]
    for _ in range(300):
        o = owners[rng.integers(len(owners))]
        op = rng.integers(3)
        try:
            if op == 0:
                a.alloc(o, int(rng.integers(0, 4)))
            elif op == 1:
                a.ensure(o, int(rng.integers(1, 40)))
            else:
                a.free(o)
        except PoolExhausted as e:
            assert e.requested > e.free
        _check_invariants(a)


def test_kvpool_int8_seal_covers_scales():
    """The q8 digest covers the scale pages: corrupting ONLY a scale
    (payload bytes intact) must fail the verify."""
    from icikit.models.transformer.model import make_model_mesh
    from icikit.serve.kvpool import KVPool

    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    pool = KVPool(_tiny_cfg(), mesh, n_blocks=4, block_size=4,
                  quant="int8")
    table = pool.allocators[0].alloc("req", 1)
    data = np.arange(4 * 2 * 8, dtype=np.int8).reshape(4, 2, 8)
    pool.poke_page(0, table[0], 0, data)
    pool.seal(0, table[0])
    assert pool.verify("req", 0) == []
    vsc = list(pool.vsc)
    vsc[1] = vsc[1].at[0, table[0], 2, 1].set(3.25)
    pool.vsc = tuple(vsc)
    assert pool.verify("req", 0) == [0]


def test_kvpool_rejects_unknown_quant():
    from icikit.models.transformer.model import make_model_mesh
    from icikit.serve.kvpool import KVPool

    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    with pytest.raises(ValueError, match="unknown pool quant"):
        KVPool(_tiny_cfg(), mesh, n_blocks=4, block_size=4,
               quant="fp8")


# ---------------------------------------------------------------- r11:
# refcounted sharing, the content-addressed prefix index, CoW, LRU
# eviction — the allocator invariants that make PREFIX-SHARED paged
# attention safe (ISSUE 8).


def _check_sharing_invariants(a: BlockAllocator):
    """The refcount-world conservation laws:

    - every page is in exactly one of {free, cached, live};
    - a page's refcount equals its total table occurrences;
    - cached pages are content-indexed (that is what keeps them);
    - free + cached + distinct-live == capacity.
    """
    from collections import Counter
    occ = Counter()
    for o in a.owners():
        occ.update(a.table(o))
    with a._lock:
        free = list(a._free)
        cached = list(a._cached)
        refs = dict(a._refs)
        hashed = set(a._hash_of)
        index = dict(a._index)
    assert refs == dict(occ), "refcounts drifted from table occupancy"
    live = set(refs)
    assert not live & set(free), "live page on the free list"
    assert not live & set(cached), "live page in the cached set"
    assert not set(free) & set(cached), "page both free and cached"
    assert set(cached) <= hashed, "cached page without an index entry"
    assert len(free) + len(cached) + len(live) == a.capacity, \
        "capacity not conserved across free/cached/live"
    assert all(1 <= p <= a.capacity
               for p in list(live) + free + cached), \
        "page id outside [1, capacity] (trash block 0 leaked?)"
    assert set(index.values()) <= live | set(cached), \
        "index maps a free-list page"


def test_block_hashes_chain_is_prefix_consistent():
    toks = np.arange(20, dtype=np.int32)
    h_full = block_hashes(toks, 4)
    assert len(h_full) == 5
    # the chain property that makes the flat dict a radix trie: the
    # hashes of a prefix ARE the prefix of the hashes
    assert block_hashes(toks[:12], 4) == h_full[:3]
    # ...and diverging one token past a block boundary changes only
    # the later hashes
    other = toks.copy()
    other[13] += 1
    ho = block_hashes(other, 4)
    assert ho[:3] == h_full[:3] and ho[3:] != h_full[3:]
    # side-aware: an int8 block never answers an fp lookup
    assert block_hashes(toks, 4, side="q8") != h_full
    # only FULL blocks hash (the partial tail is never shareable)
    assert len(block_hashes(toks[:11], 4)) == 2


def test_share_revives_cached_and_release_caches_indexed():
    a = BlockAllocator(8, 4)
    t = a.alloc("A", 2)
    hs = ["h0", "h1"]
    for p, h in zip(t, hs):
        assert a.register(p, h)
    n, freed = a.release("A")
    assert n == 2 and freed == []          # indexed -> cached, not freed
    assert a.n_cached == 2 and a.n_used == 0 and a.n_free == 6
    _check_sharing_invariants(a)
    # lookup walks the chain; share revives to live
    assert a.lookup(hs) == list(t)
    assert a.lookup(["h0", "WRONG"]) == [t[0]]   # chain stops at miss
    a.share("B", t)
    assert a.n_cached == 0 and a.refcount(t[0]) == 1
    a.share("C", t)
    assert a.refcount(t[0]) == 2
    _check_sharing_invariants(a)
    # releases peel refcounts; last one re-caches
    a.release("B")
    assert a.refcount(t[0]) == 1 and a.n_cached == 0
    a.release("C")
    assert a.n_cached == 2
    _check_sharing_invariants(a)


def test_cow_forks_only_shared_blocks():
    a = BlockAllocator(8, 4)
    t = a.alloc("A", 2)
    assert a.register(t[0], "h0")
    a.share("B", [t[0]])
    # exclusive block: no fork
    assert a.cow("A", 1) is None
    # shared block: B forks, tables stop aliasing, refcounts settle
    pair = a.cow("B", 0)
    assert pair is not None
    old, new = pair
    assert old == t[0] and new not in t
    assert a.table("B") == (new,) and a.table("A") == t
    assert a.refcount(old) == 1 and a.refcount(new) == 1
    # the fork is anonymous: the content address stays with the
    # original, so the fork frees (not caches) on release
    _, freed = a.release("B")
    assert freed == [new]
    _check_sharing_invariants(a)


def test_lru_eviction_under_pressure_and_honest_exhaustion():
    a = BlockAllocator(4, 4)
    t = a.alloc("A", 4)
    for i, p in enumerate(t):
        a.register(p, f"h{i}")
    a.release("A")
    assert a.n_cached == 4 and a.n_free == 0
    # touch h2's chain position -> h0 stays LRU... lookup touches the
    # pages it returns, so look up the chain prefix ending at h1
    a.lookup(["h0", "h1"])
    # allocation evicts the LRU cached pages (h2, h3 were untouched
    # longest? no: insertion order h0..h3, lookup revived h0,h1 to MRU
    # -> LRU victims are h2 then h3)
    got = a.alloc("B", 2)
    assert set(got) == {t[2], t[3]}
    assert a.n_evictions == 2
    assert a.indexed("h2") is None and a.indexed("h0") == t[0]
    _check_sharing_invariants(a)
    # exhaustion counts reclaimable (free + cached), not just free
    with pytest.raises(PoolExhausted) as ei:
        a.alloc("B", 3)
    assert ei.value.free == 2              # the two cached survivors
    _check_sharing_invariants(a)
    # live blocks pin: share a cached page, then over-ask
    a.share("C", [t[0]])
    with pytest.raises(PoolExhausted):
        a.alloc("D", 2)                    # only h1 reclaimable now
    _check_sharing_invariants(a)


def test_deregister_quarantines_from_reuse():
    a = BlockAllocator(4, 4)
    [p] = a.alloc("A", 1)
    a.register(p, "h")
    # live quarantine: index entry gone, page drains to FREE on release
    assert a.deregister(p)
    assert not a.deregister(p)             # idempotent
    assert a.indexed("h") is None
    _, freed = a.release("A")
    assert freed == [p]
    _check_sharing_invariants(a)
    # cached quarantine: page moves cached -> free immediately
    [p2] = a.alloc("B", 1)
    a.register(p2, "h2")
    a.release("B")
    assert a.n_cached == 1
    assert a.deregister(p2)
    assert a.n_cached == 0 and a.n_free == 4
    _check_sharing_invariants(a)


def test_refcount_cow_property_fuzz():
    """Random interleavings of the FULL r11 allocator surface —
    alloc/ensure/release/register/lookup+share/cow — holding the
    sharing conservation laws at every step, ending in a drained
    allocator at full capacity. The classic invariants (no aliasing
    WITHIN the exclusive world, honest exhaustion) ride along via the
    refcount==occupancy law."""
    rng = np.random.default_rng(11)
    for trial in range(20):
        cap = int(rng.integers(6, 32))
        bs = int(rng.integers(1, 6))
        a = BlockAllocator(cap, bs)
        owners = [f"r{i}" for i in range(int(rng.integers(2, 7)))]
        minted = 0
        for stepi in range(250):
            o = owners[int(rng.integers(0, len(owners)))]
            op = rng.integers(0, 6)
            try:
                if op == 0:
                    a.alloc(o, int(rng.integers(0, 4)))
                elif op == 1:
                    a.ensure(o, int(rng.integers(1, cap * bs + 1)))
                elif op == 2:
                    a.release(o)
                elif op == 3:
                    # register a random owned page under a fresh hash
                    t = a.table(o)
                    if t:
                        p = t[int(rng.integers(0, len(t)))]
                        a.register(p, f"h{minted}")
                        minted += 1
                elif op == 4:
                    # look up a random known hash chain and share it
                    if minted:
                        h = f"h{int(rng.integers(0, minted))}"
                        pages = a.lookup([h])
                        if pages:
                            a.share(o, pages)
                else:
                    t = a.table(o)
                    if t:
                        idx = int(rng.integers(0, len(t)))
                        before = a.table(o)[idx]
                        pair = a.cow(o, idx)
                        if pair is not None:
                            old, new = pair
                            assert old == before
                            # THE CoW law: after a fork, no other
                            # owner's table maps the fork
                            for o2 in a.owners():
                                if o2 != o:
                                    assert new not in a.table(o2)
                            assert a.refcount(new) == 1
            except PoolExhausted as e:
                assert e.requested > e.free     # raised honestly
            _check_sharing_invariants(a)
        for o in owners:
            a.release(o)
        _check_sharing_invariants(a)
        # drain the cache too: evicting everything returns the pool
        # to mint condition
        a.alloc("drain", cap)
        a.release("drain")
        assert a.n_free == cap and a.n_cached == 0 and a.n_used == 0


def test_pool_cow_copies_device_bytes_and_seal():
    """KVPool.cow must copy every arena's bytes for the forked page
    (all layers) and carry the content seal — the fork IS the sealed
    content until somebody writes it."""
    import jax

    from icikit.models.transformer import TransformerConfig, init_params
    from icikit.models.transformer.model import make_model_mesh
    from icikit.serve.kvpool import KVPool

    del jax, init_params
    cfg = _tiny_cfg()
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    pool = KVPool(cfg, mesh, n_blocks=8, block_size=4)
    [p] = pool.allocators[0].alloc("A", 1)
    data = np.arange(4 * 2 * 8, dtype=np.float32).reshape(4, 2, 8)
    for li in range(cfg.n_layers):
        pool.poke_page(0, p, li, data + li)
    pool.seal(0, p)
    pool.allocators[0].register(p, "h")
    pool.share("B", 0, [p])
    pair = pool.cow("B", 0, 0)
    assert pair is not None
    old, new = pair
    assert old == p
    for li in range(cfg.n_layers):
        np.testing.assert_array_equal(pool.read_page(0, new, li),
                                      pool.read_page(0, old, li))
    # the fork's seal verifies (content bitwise copied)
    assert pool.verify("B", 0) == []
    # ...and diverging the fork fails ONLY the fork's owner
    bad = np.array(data)
    bad[0, 0, 0] += 7.0
    pool.poke_page(0, new, 0, bad)
    assert pool.verify("B", 0) == [0]
    assert pool.verify("A", 0) == []


def test_eviction_takes_chain_leaves_before_roots():
    """Chain-order LRU discipline: release parks the chain ROOT at
    the MRU end (lookup can only walk a chain from its root, so
    evicting a root orphans every deeper cached block); eviction
    under pressure must therefore take the deepest block first,
    leaving a shorter but WALKABLE prefix."""
    a = BlockAllocator(3, 2)
    t = a.alloc("A", 3)
    for i, p in enumerate(t):
        a.register(p, f"c{i}")
    a.release("A")
    assert a.n_cached == 3 and a.n_free == 0
    [got] = a.alloc("B", 1)        # pressure: one eviction
    assert got == t[2]             # the DEEPEST block, not the root
    assert a.lookup(["c0", "c1", "c2"]) == [t[0], t[1]]
    _check_sharing_invariants(a)


# ---------------------------------------------------------------- r16:
# the tiered allocator — spilled as the fourth content state, host-
# tier conservation, restore/adopt, and the honest-accounting pins
# (ISSUE 13).


def _attach_fake_host(a: BlockAllocator):
    """Allocator-level host tier: captures are plain dict entries, so
    the fuzz can hold the tier-mirror invariant without any device
    arenas. Returns the backing dict."""
    host = {}

    def spill(pairs):
        for page, h in pairs:
            host[h] = ("payload", page)
        return {h for _, h in pairs}

    def drop(h, demote=True):
        host.pop(h, None)

    a.spill_cb = spill
    a.drop_cb = drop
    return host


def _check_tier_invariants(a: BlockAllocator, host: dict):
    """The 4-state conservation laws on top of the r11 sharing laws:

    - device pages still partition exactly into free/cached/live
      (free + cached + live == capacity — spilled holds NO page);
    - the spilled set mirrors the host tier exactly and is bounded by
      host_blocks;
    - spilled content is never simultaneously index-resident (one
      source of truth per hash).
    """
    _check_sharing_invariants(a)
    with a._lock:
        spilled = set(a._spilled)
        indexed = set(a._index)
    assert spilled == set(host), "host tier drifted from spilled set"
    assert len(spilled) <= a.host_blocks, "host tier over capacity"
    assert not spilled & indexed, \
        "hash both spilled and index-resident"


def test_spill_tier_4state_conservation_fuzz():
    """Random interleavings over the FULL tiered surface —
    alloc/ensure/release/register/lookup+share/cow/adopt — holding
    device conservation AND the tier mirror at every step. Restores
    (adopt) must never alias: the adopted page is fresh, exclusive,
    and index-resident under the restored hash."""
    rng = np.random.default_rng(23)
    for trial in range(12):
        cap = int(rng.integers(6, 24))
        bs = int(rng.integers(1, 5))
        hb = int(rng.integers(1, 12))
        a = BlockAllocator(cap, bs, host_blocks=hb)
        host = _attach_fake_host(a)
        owners = [f"r{i}" for i in range(int(rng.integers(2, 6)))]
        minted = 0
        for _ in range(300):
            o = owners[int(rng.integers(0, len(owners)))]
            op = rng.integers(0, 7)
            try:
                if op == 0:
                    a.alloc(o, int(rng.integers(0, 4)))
                elif op == 1:
                    a.ensure(o, int(rng.integers(1, cap * bs + 1)))
                elif op == 2:
                    a.release(o)
                elif op == 3:
                    t = a.table(o)
                    if t:
                        p = t[int(rng.integers(0, len(t)))]
                        a.register(p, f"h{minted}")
                        minted += 1
                elif op == 4:
                    if minted:
                        h = f"h{int(rng.integers(0, minted))}"
                        pages = a.lookup([h])
                        if pages:
                            a.share(o, pages)
                elif op == 5:
                    # restore a random spilled hash: the page comes
                    # back fresh, exclusive, and indexed
                    with a._lock:
                        sp = list(a._spilled)
                    if sp:
                        h = sp[int(rng.integers(0, len(sp)))]
                        page = a.adopt(o, h)
                        if page is not None:
                            assert a.refcount(page) == 1
                            assert a.indexed(h) == page
                            assert not a.spilled(h)
                else:
                    t = a.table(o)
                    if t:
                        a.cow(o, int(rng.integers(0, len(t))))
            except PoolExhausted as e:
                assert e.requested > e.free     # raised honestly
                assert e.spilled == a.n_spilled
            _check_tier_invariants(a, host)
        for o in owners:
            a.release(o)
        _check_tier_invariants(a, host)


def test_pool_exhausted_accounts_spilled_distinctly():
    """The r16 accounting fix: a spilled block is reclaimable
    CAPACITY but not a device page — PoolExhausted must report it
    beside (never inside) the device-reclaimable count, and pool
    occupancy stays live-only."""
    a = BlockAllocator(4, 4, host_blocks=8)
    _attach_fake_host(a)
    t = a.alloc("A", 4)
    for i, p in enumerate(t):
        a.register(p, f"s{i}")
    a.release("A")
    a.alloc("B", 4)               # evicts+spills all four
    assert a.n_spilled == 4
    with pytest.raises(PoolExhausted) as ei:
        a.alloc("C", 2)
    assert ei.value.free == 0            # nothing device-reclaimable
    assert ei.value.spilled == 4         # reported distinctly
    assert "spilled to the host tier" in str(ei.value)
    # an allocator without a tier reports spilled == 0 and the
    # pre-r16 message shape
    b = BlockAllocator(2, 4)
    b.alloc("A", 2)
    with pytest.raises(PoolExhausted) as ei2:
        b.alloc("B", 1)
    assert ei2.value.spilled == 0
    assert "spilled" not in str(ei2.value)


def test_pool_occupancy_ignores_spilled_and_gauges_spilled():
    """Occupancy counts LIVE blocks only: content in the host tier
    must move neither occupancy nor the cached count — it is tracked
    by its own figure (`spilled_blocks`, the serve.kv.spilled
    gauge)."""
    from icikit.models.transformer.model import make_model_mesh
    from icikit.serve.kvpool import KVPool

    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    pool = KVPool(_tiny_cfg(), mesh, n_blocks=4, block_size=4,
                  host_blocks=8)
    a = pool.allocators[0]
    t = a.alloc("A", 4)
    for i, p in enumerate(t):
        pool.seal(0, p)
        a.register(p, f"o{i}")
    pool.release("A", 0)
    assert pool.occupancy() == 0.0       # cached, not live
    a.alloc("B", 4)                      # all four spill
    assert pool.spilled_blocks() == 4
    assert pool.occupancy() == 1.0       # B's live pages only
    pool.release("B", 0)
    assert pool.occupancy() == 0.0
    assert pool.spilled_blocks() == 4    # spilled content unaffected


def test_q8_spill_restores_scales_and_verifies_with_blocks():
    """int8 arenas: the spilled payload must carry the SCALE pages
    with the quantized blocks, the swap-in digest must cover both,
    and a flipped scale in the host copy must fail the verify and
    quarantine the content (never trusted, recompute instead)."""
    from icikit.models.transformer.model import make_model_mesh
    from icikit.serve.kvpool import KVPool, _page_digest

    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    pool = KVPool(_tiny_cfg(), mesh, n_blocks=4, block_size=4,
                  quant="int8", host_blocks=8)
    a = pool.allocators[0]
    [p1, p2] = a.alloc("A", 2)
    data = np.arange(4 * 2 * 8, dtype=np.int8).reshape(4, 2, 8)
    for li in range(2):
        for p in (p1, p2):
            pool.poke_page(0, p, li, data + p + li)
    for i, p in enumerate((p1, p2)):
        pool.seal(0, p)
        a.register(p, f"q{i}")
    pool.release("A", 0)
    a.alloc("B", 4)                      # both spill
    assert a.n_spilled == 2
    pool.release("B", 0)                 # free device room to restore
    # clean restore: scales ride along bitwise
    out = pool.restore_block("C", 0, "q0")
    assert isinstance(out, dict)
    page = a.table("C")[0]
    np.testing.assert_array_equal(
        pool.read_page(0, page, 1, side="q8"), data + p1 + 1)
    assert pool.verify("C", 0) == []
    # the q8 payload interleaves scale pages: 4 arrays per layer
    rec = pool._materialize(0, "q1")
    assert len(rec[2]) == 4 * pool.cfg.n_layers
    assert _page_digest(rec[2]) == rec[1]
    # flip ONE scale value in the host copy -> swap-in verify fails,
    # content quarantined from the tier
    rec[2][2] = np.array(rec[2][2])      # ksc page of layer 0
    rec[2][2].flat[3] += 0.5
    assert pool.restore_block("D", 0, "q1") is None
    assert not a.spilled("q1")           # quarantined, not retryable
    assert a.table("D") == ()
