"""KV-block allocator property/fuzz suite + pool integrity unit tests.

The allocator is pure host metadata, so the fuzz loop can hammer
thousands of random alloc/extend/free interleavings and check the
invariants that make paged attention safe:

- live block tables never alias (a block serves exactly one owner);
- the free list conserves capacity (free + live == capacity, no block
  minted or leaked, ever);
- exhaustion raises :class:`PoolExhausted` cleanly — all-or-nothing,
  allocator state unchanged;
- ``free`` is idempotent and block 0 (the trash block) is never
  handed out.
"""

import numpy as np
import pytest

from icikit.serve.kvpool import BlockAllocator, PoolExhausted


def _check_invariants(a: BlockAllocator):
    live = []
    for o in a.owners():
        live.extend(a.table(o))
    assert len(live) == len(set(live)), "live blocks alias"
    assert all(1 <= b <= a.capacity for b in live), \
        "allocated id outside [1, capacity] (trash block 0 leaked?)"
    assert a.n_free + len(live) == a.capacity, "capacity not conserved"


def test_alloc_free_roundtrip():
    a = BlockAllocator(8, 4)
    t = a.alloc("r0", 3)
    assert len(t) == 3 and a.table("r0") == t
    assert a.n_free == 5
    assert a.free("r0") == 3
    assert a.n_free == 8
    assert a.free("r0") == 0          # idempotent
    assert a.n_free == 8


def test_ensure_grows_to_token_count():
    a = BlockAllocator(8, 4)
    assert len(a.ensure("r", 1)) == 1     # 1 token -> 1 block
    assert len(a.ensure("r", 4)) == 0     # still covered
    assert len(a.ensure("r", 5)) == 1     # crosses the boundary
    assert len(a.ensure("r", 17)) == 3    # ceil(17/4) = 5 total
    assert len(a.table("r")) == 5


def test_exhaustion_is_all_or_nothing():
    a = BlockAllocator(4, 4)
    a.alloc("r0", 3)
    before_free = a.n_free
    before_table = a.table("r0")
    with pytest.raises(PoolExhausted) as ei:
        a.alloc("r1", 2)
    assert ei.value.requested == 2 and ei.value.free == 1
    assert a.n_free == before_free          # nothing handed out
    assert a.table("r0") == before_table
    assert a.table("r1") == ()
    _check_invariants(a)


def test_fuzz_interleavings_never_alias():
    """Random alloc/ensure/free streams across many owners: the three
    safety invariants hold at every step, and a drained allocator
    always returns to full capacity."""
    rng = np.random.default_rng(7)
    for trial in range(30):
        cap = int(rng.integers(4, 40))
        bs = int(rng.integers(1, 9))
        a = BlockAllocator(cap, bs)
        owners = [f"r{i}" for i in range(int(rng.integers(2, 9)))]
        for _ in range(200):
            op = rng.integers(0, 3)
            o = owners[int(rng.integers(0, len(owners)))]
            try:
                if op == 0:
                    a.alloc(o, int(rng.integers(0, 5)))
                elif op == 1:
                    a.ensure(o, int(rng.integers(1, cap * bs + 1)))
                else:
                    a.free(o)
            except PoolExhausted as e:
                assert e.requested > e.free    # raised honestly
            _check_invariants(a)
        for o in owners:
            a.free(o)
        assert a.n_free == cap


def test_kvpool_seal_verify_detects_poke():
    """The integrity path end-to-end at pool level: seal a page,
    corrupt it via poke_page, verify flags exactly that block — the
    mechanism behind the serve.kv.page containment drill."""
    import jax

    from icikit.models.transformer import TransformerConfig, init_params
    from icikit.models.transformer.model import make_model_mesh
    from icikit.serve.kvpool import KVPool

    cfg = TransformerConfig(vocab=31, d_model=16, n_heads=2, d_head=8,
                            d_ff=32, n_layers=2, max_seq=32,
                            compute_dtype="float32")
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    init_params(jax.random.key(0), cfg, mesh)  # exercise cfg checks
    pool = KVPool(cfg, mesh, n_blocks=8, block_size=4)
    table = pool.allocators[0].alloc("req", 2)
    # write something nonzero into both pages, then seal them
    data = np.arange(4 * 2 * 8, dtype=np.float32).reshape(4, 2, 8)
    for bi, page in enumerate(table):
        pool.poke_page(0, page, 0, data + bi)
        pool.seal("req", 0, bi, page)
    assert pool.verify("req", 0) == []
    flipped = np.array(data)
    flipped[0, 0, 0] += 1.0
    pool.poke_page(0, table[1], 0, flipped + 1)
    assert pool.verify("req", 0) == [1]
    pool.drop_seals("req", 0)
    assert pool.verify("req", 0) == []


def test_kvpool_occupancy_and_fragmentation():
    import jax

    from icikit.models.transformer import TransformerConfig
    from icikit.models.transformer.model import make_model_mesh
    from icikit.serve.kvpool import KVPool

    del jax
    cfg = TransformerConfig(vocab=31, d_model=16, n_heads=2, d_head=8,
                            d_ff=32, n_layers=1, max_seq=32,
                            compute_dtype="float32")
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    pool = KVPool(cfg, mesh, n_blocks=8, block_size=4)
    assert pool.occupancy() == 0.0
    pool.ensure("a", 0, 6)      # 2 blocks for 6 tokens
    assert pool.occupancy() == pytest.approx(2 / 8)
    # 6 of 8 allocated slots used -> fragmentation 0.25
    assert pool.fragmentation({("a", 0): 6}) == pytest.approx(0.25)
    pool.free("a", 0)
    assert pool.occupancy() == 0.0


def _tiny_cfg():
    from icikit.models.transformer import TransformerConfig
    return TransformerConfig(vocab=31, d_model=16, n_heads=2, d_head=8,
                             d_ff=32, n_layers=2, max_seq=32,
                             compute_dtype="float32")


@pytest.mark.parametrize("quant", ["int8", "mixed"])
def test_kvpool_int8_arenas_and_allocator_properties(quant):
    """int8/mixed pools: arena dtypes + the allocator property run on
    the quantized pool (the allocator is arena-independent by design,
    and this pins that the int8 wiring kept it so)."""
    import jax.numpy as jnp

    from icikit.models.transformer.model import make_model_mesh
    from icikit.serve.kvpool import KVPool

    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    pool = KVPool(_tiny_cfg(), mesh, n_blocks=8, block_size=4,
                  quant=quant)
    assert pool.qkc[0].dtype == jnp.int8
    assert pool.ksc[0].dtype == jnp.float32
    assert pool.ksc[0].shape == pool.qkc[0].shape[:-1]
    if quant == "int8":
        assert pool.kc is None          # no fp arena on the int8 path
        assert set(pool.buffers()) == {"qkc", "qvc", "ksc", "vsc"}
    else:
        assert pool.kc is not None
        assert set(pool.buffers()) == {"kc", "vc", "qkc", "qvc",
                                       "ksc", "vsc"}
    rng = np.random.default_rng(13)
    a = pool.allocators[0]
    owners = [f"r{i}" for i in range(5)]
    for _ in range(300):
        o = owners[rng.integers(len(owners))]
        op = rng.integers(3)
        try:
            if op == 0:
                a.alloc(o, int(rng.integers(0, 4)))
            elif op == 1:
                a.ensure(o, int(rng.integers(1, 40)))
            else:
                a.free(o)
        except PoolExhausted as e:
            assert e.requested > e.free
        _check_invariants(a)


def test_kvpool_int8_seal_covers_scales():
    """The q8 digest covers the scale pages: corrupting ONLY a scale
    (payload bytes intact) must fail the verify."""
    from icikit.models.transformer.model import make_model_mesh
    from icikit.serve.kvpool import KVPool

    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    pool = KVPool(_tiny_cfg(), mesh, n_blocks=4, block_size=4,
                  quant="int8")
    table = pool.allocators[0].alloc("req", 1)
    data = np.arange(4 * 2 * 8, dtype=np.int8).reshape(4, 2, 8)
    pool.poke_page(0, table[0], 0, data)
    pool.seal("req", 0, 0, table[0])
    assert pool.verify("req", 0) == []
    vsc = list(pool.vsc)
    vsc[1] = vsc[1].at[0, table[0], 2, 1].set(3.25)
    pool.vsc = tuple(vsc)
    assert pool.verify("req", 0) == [0]


def test_kvpool_rejects_unknown_quant():
    from icikit.models.transformer.model import make_model_mesh
    from icikit.serve.kvpool import KVPool

    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    with pytest.raises(ValueError, match="unknown pool quant"):
        KVPool(_tiny_cfg(), mesh, n_blocks=4, block_size=4,
               quant="fp8")
