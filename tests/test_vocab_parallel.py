"""Vocab-parallel (Megatron) output head: each tp shard holds V/tp
logits; cross-entropy closes with a gathered max, a psum'd logsumexp,
and an owner-shard masked psum for the target logit. Must match the
replicated head bit-for-nearly-bit in loss, gradients, and decode."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from icikit.models.transformer import (
    TransformerConfig,
    greedy_generate,
    init_params,
    loss_fn,
)
from icikit.models.transformer.model import make_model_mesh

BASE = dict(vocab=64, d_model=32, n_heads=4, d_head=8, d_ff=64,
            n_layers=2, max_seq=32, compute_dtype="float32")


def _data(seed=0):
    rng = np.random.default_rng(seed)
    return (jnp.asarray(rng.integers(0, 64, (4, 32)), jnp.int32),
            jnp.asarray(rng.integers(0, 64, (4, 32)), jnp.int32))


def _run(vp, dp, tp, sp, tok, tgt):
    cfg = TransformerConfig(**BASE, vocab_parallel=vp)
    mesh = make_model_mesh(dp=dp, tp=tp, sp=sp)
    params = init_params(jax.random.key(0), cfg, mesh)
    sh = NamedSharding(mesh, P("dp", "sp"))
    loss, grads = loss_fn(params, jax.device_put(tok, sh),
                          jax.device_put(tgt, sh), mesh, cfg)
    return float(loss), jax.device_get(grads)


@pytest.mark.parametrize("dp,tp,sp", [(1, 4, 1), (2, 2, 2)])
def test_matches_replicated_head(dp, tp, sp):
    tok, tgt = _data()
    l0, g0 = _run(False, 1, 1, 1, tok, tgt)
    l1, g1 = _run(True, dp, tp, sp, tok, tgt)
    assert l0 == pytest.approx(l1, rel=2e-5)
    assert set(g0) == set(g1)
    for k in g0:
        np.testing.assert_allclose(np.asarray(g1[k]), np.asarray(g0[k]),
                                   atol=5e-5, rtol=5e-4, err_msg=k)


def test_w_out_actually_sharded():
    cfg = TransformerConfig(**BASE, vocab_parallel=True)
    mesh = make_model_mesh(dp=1, tp=4, sp=1)
    params = init_params(jax.random.key(0), cfg, mesh)
    spec = params["w_out"].sharding.spec
    assert spec == P("tp", None)


def test_decode_matches_replicated():
    tok, _ = _data(1)
    cfg = TransformerConfig(**BASE, vocab_parallel=True)
    mesh = make_model_mesh(dp=1, tp=4, sp=1)
    params = init_params(jax.random.key(0), cfg, mesh)
    pd = jax.device_put(tok[:, :8], NamedSharding(mesh, P("dp", None)))
    got = np.asarray(greedy_generate(params, pd, mesh, cfg, n_new=4))

    cfg0 = TransformerConfig(**BASE, vocab_parallel=False)
    mesh0 = make_model_mesh(dp=1, tp=1, sp=1)
    params0 = init_params(jax.random.key(0), cfg0, mesh0)
    want = np.asarray(greedy_generate(params0, jnp.asarray(tok[:, :8]),
                                      mesh0, cfg0, n_new=4))
    np.testing.assert_array_equal(got, want)


def test_uneven_vocab_rejected():
    cfg = TransformerConfig(**dict(BASE, vocab=61), vocab_parallel=True)
    mesh = make_model_mesh(dp=1, tp=4, sp=1)
    with pytest.raises(ValueError, match="vocab_parallel requires"):
        init_params(jax.random.key(0), cfg, mesh)


def test_pipeline_path_rejects_vocab_parallel():
    from icikit.models.transformer.pipeline import pp_param_specs
    cfg = TransformerConfig(**BASE, vocab_parallel=True)
    with pytest.raises(ValueError, match="vocab_parallel"):
        pp_param_specs(cfg)
