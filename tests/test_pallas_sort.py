"""Pallas sorting-kernel tests, run through the Pallas interpreter on CPU.

Mirrors the reference's sorted-order oracle (psort.cc:497-520) at the
single-device level: every configuration is checked against ``np.sort``.
Small tile geometries exercise all three kernel paths (single-tile
network, gridded tile sort + merge rounds, and multi-pass cross-tile
rounds) without TPU hardware.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from icikit.ops import pallas_sort as ps

RNG = np.random.default_rng(7)


def _ints(n):
    return RNG.integers(-2**31, 2**31 - 1, size=n, dtype=np.int32)


def test_single_tile_sort_int32():
    x = _ints(1 << 13)
    out = np.asarray(ps.local_sort(jnp.asarray(x), backend="interpret"))
    assert np.array_equal(out, np.sort(x))


def test_multi_phase_sort():
    # n > t_big: tile-sort pass + single-tile merge rounds + cross rounds
    x = _ints(1 << 14)
    out = np.asarray(ps.local_sort(
        jnp.asarray(x), backend="interpret", t_grid=1 << 11, t_big=1 << 12))
    assert np.array_equal(out, np.sort(x))


def test_multi_range_cross_rounds():
    # g_max=1 forces every cross round to split into several bit-range
    # passes, covering the (A, G, B) grid-folding path.
    x = _ints(1 << 14)
    out = np.asarray(ps.local_sort(
        jnp.asarray(x), backend="interpret", t_grid=1 << 11, t_big=1 << 11,
        g_max=1))
    assert np.array_equal(out, np.sort(x))


def test_float32_and_nonpow2_padding():
    x = RNG.standard_normal(10000).astype(np.float32)
    out = np.asarray(ps.local_sort(jnp.asarray(x), backend="interpret"))
    assert np.array_equal(out, np.sort(x))


def test_uint32():
    x = RNG.integers(0, 2**32, size=1 << 13, dtype=np.uint32)
    out = np.asarray(ps.local_sort(jnp.asarray(x), backend="interpret"))
    assert np.array_equal(out, np.sort(x))


def test_small_input_uses_xla():
    assert ps._resolve_backend("auto", jnp.int32, 128) == "xla"
    x = _ints(128)
    out = np.asarray(ps.local_sort(jnp.asarray(x)))
    assert np.array_equal(out, np.sort(x))


def test_unsupported_dtype_raises():
    x = jnp.zeros((1 << 13,), jnp.int16)
    with pytest.raises(ValueError, match="pallas sort supports"):
        ps.local_sort(x, backend="pallas")


def test_env_opts_into_interpret(monkeypatch):
    monkeypatch.setenv("ICIKIT_PALLAS", "interpret")
    assert ps._resolve_backend("auto", jnp.int32, 1 << 13) == "interpret"
    assert ps._resolve_backend("auto", jnp.int16, 1 << 13) == "xla"


def _bitonic(n, hi=10**6):
    a = np.sort(RNG.integers(0, hi, n // 2).astype(np.int32))
    b = np.sort(RNG.integers(0, hi, n // 2).astype(np.int32))[::-1]
    return np.concatenate([a, b])


def test_merge_bitonic_single_tile():
    v = _bitonic(1 << 13)
    out = np.asarray(ps.merge_bitonic(jnp.asarray(v), backend="interpret"))
    assert np.array_equal(out, np.sort(v))


def test_merge_bitonic_cross_rounds():
    v = _bitonic(1 << 14)
    out = np.asarray(ps.merge_bitonic(
        jnp.asarray(v), backend="interpret", t_grid=1 << 11, t_big=1 << 12))
    assert np.array_equal(out, np.sort(v))


def test_merge_requires_pow2():
    with pytest.raises(ValueError, match="power-of-2"):
        ps.merge_bitonic(jnp.zeros((3000,), jnp.int32), backend="interpret")


def test_merge_validates_dtype_and_size():
    with pytest.raises(ValueError, match="pallas merge supports"):
        ps.merge_bitonic(jnp.zeros((64,), jnp.int32), backend="interpret")
    with pytest.raises(ValueError, match="pallas merge supports"):
        ps.merge_bitonic(jnp.zeros((1 << 13,), jnp.int16),
                         backend="interpret")


def test_merge_xla_fallback_matches():
    v = _bitonic(1 << 10)
    out = np.asarray(ps.merge_bitonic(jnp.asarray(v), backend="xla"))
    assert np.array_equal(out, np.sort(v))


def test_local_sort_bf16_widen_narrow():
    """bf16 keys sort exactly through the fp32 path (bf16 embeds in
    f32; the mapping is monotone)."""
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.standard_normal(1 << 14).astype(np.float32)
                    ).astype(jnp.bfloat16)
    out = ps.local_sort(x, backend="interpret")
    assert out.dtype == jnp.bfloat16
    want = np.sort(np.asarray(x, np.float32))
    np.testing.assert_array_equal(np.asarray(out, np.float32), want)
