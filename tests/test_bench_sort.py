"""The sorting-study benchmark harness: every algorithm verified and
timed over the sweep (the reference driver's sort/check_sort/report
loop, psort.cc:525-663, as a testable API)."""

import pytest

from icikit.bench.sort import format_table, sweep_sorts


@pytest.mark.parametrize("odd_dist", [False, True])
def test_sweep_sorts_all_algorithms(mesh8, odd_dist):
    records = sweep_sorts(mesh8, sizes=(4096,), runs=2, warmup=1,
                          odd_dist=odd_dist)
    assert {r.algorithm for r in records} == {
        "bitonic", "sample", "sample_bitonic", "quicksort"}
    for r in records:
        assert r.errors == 0, f"{r.algorithm} produced inversions"
        assert r.keys_per_s > 0
        assert r.p == 8
    table = format_table(records)
    assert "bitonic" in table and "Mkeys/s" in table


def test_sweep_sorts_float_and_non_pow2_skip():
    from icikit.utils.mesh import make_mesh
    mesh = make_mesh(6)
    records = sweep_sorts(mesh, sizes=(4096,), runs=2, warmup=1,
                          dtype="float32")
    # bitonic requires power-of-2 p and is skipped on 6 devices
    algs = {r.algorithm for r in records}
    assert "bitonic" not in algs
    assert "sample" in algs
    assert all(r.errors == 0 for r in records)
    assert all(r.dtype == "float32" for r in records)
