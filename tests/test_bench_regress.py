"""Bench regression gate (`tools/bench_regress.py`): paired arms by
config key, provenance separation, median-of-seeds, noise-widened
tolerance bands, injected-regression drill, verdict files."""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                "tools"))
import bench_regress as br  # noqa: E402


def _row(backend="cpu", mode="continuous", seed=0, tps=100.0, **over):
    row = {"kind": "serve", "preset": "tiny", "backend": backend,
           "mode": mode, "rows": 4, "rate_rps": 8.0, "seed": seed,
           "tokens_per_s": tps,
           "ttft_ms": {"p50": 50.0, "p99": 120.0}}
    row.update(over)
    return row


def test_config_key_pairs_arms_and_pools_seeds():
    a, b = _row(seed=0, tps=100.0), _row(seed=1, tps=110.0)
    assert br.config_key(a) == br.config_key(b)     # seeds pool
    assert br.config_key(_row(mode="static")) != br.config_key(a)
    assert br.config_key(_row(rows=8)) != br.config_key(a)


def test_provenance_separation_cpu_never_gates_tpu():
    base = [_row(backend="tpu", tps=1000.0)]
    fresh = [_row(backend="cpu", tps=100.0)]    # 10x "slower" — but
    v = br.compare(base, fresh)                 # different provenance
    assert v["ok"] and v["paired_arms"] == 0
    assert v["fresh_only_arms"] == 1 and v["baseline_only_arms"] == 1


def test_identical_ledger_passes():
    rows = [_row(seed=s, tps=100.0 + s) for s in range(3)]
    v = br.compare(rows, rows)
    assert v["ok"] and v["paired_arms"] == 1 and v["compared"] >= 1
    assert v["regressions"] == [] and v["improvements"] == []


def test_flags_20pct_throughput_regression():
    base = [_row(seed=s, tps=100.0) for s in range(3)]
    fresh = [_row(seed=s, tps=80.0) for s in range(3)]
    v = br.compare(base, fresh)
    assert not v["ok"]
    (reg,) = [r for r in v["regressions"]
              if r["metric"] == "tokens_per_s"]
    assert reg["ratio"] == pytest.approx(0.8)
    assert reg["n_baseline"] == 3 and reg["n_fresh"] == 3


def test_median_of_seeds_absorbs_one_outlier():
    base = [_row(seed=s, tps=100.0) for s in range(3)]
    fresh = [_row(seed=0, tps=99.0), _row(seed=1, tps=98.0),
             _row(seed=2, tps=20.0)]            # one bad replica
    v = br.compare(base, fresh)                 # median 98: in band
    assert v["ok"]


def test_band_widens_to_baseline_noise():
    # baseline spread ±30%: a 15% drop is inside the noise floor even
    # though the configured band is 10%
    base = [_row(seed=0, tps=70.0), _row(seed=1, tps=100.0),
            _row(seed=2, tps=130.0)]
    fresh = [_row(seed=s, tps=85.0) for s in range(3)]
    v = br.compare(base, fresh)
    assert v["ok"]


def test_lower_is_better_direction():
    base = [_row(tps=100.0)]
    fresh = [_row(tps=100.0)]
    fresh[0]["ttft_ms"] = {"p50": 500.0, "p99": 600.0}  # 10x worse
    v = br.compare(base, fresh)
    assert not v["ok"]
    assert any(r["metric"] == "ttft_ms.p50"
               for r in v["regressions"])


def test_improvements_reported_not_failed():
    base = [_row(tps=100.0)]
    fresh = [_row(tps=150.0)]
    v = br.compare(base, fresh)
    assert v["ok"] and any(i["metric"] == "tokens_per_s"
                           for i in v["improvements"])


def test_tracing_false_pairs_with_historical_rows():
    """A fresh disarmed row (tracing: False — the r15 A/B field) must
    pair with committed pre-r15 rows that predate the field; armed
    rows stay a distinct arm (they are slower by design)."""
    old = _row(tps=100.0)                       # no "tracing" key
    disarmed = _row(tps=100.0, tracing=False)
    armed = _row(tps=96.0, tracing=True)
    assert br.config_key(disarmed) == br.config_key(old)
    assert br.config_key(armed) != br.config_key(old)
    v = br.compare([old], [disarmed])
    assert v["paired_arms"] == 1


def test_gate_mode_zero_pairs_fails_by_default(tmp_path):
    base = tmp_path / "base.jsonl"
    fresh = tmp_path / "fresh.jsonl"
    base.write_text(json.dumps(_row(rows=4)) + "\n")
    fresh.write_text(json.dumps(_row(rows=64)) + "\n")   # never pairs
    rc = br.main(["--baseline", str(base), "--fresh", str(fresh)])
    assert rc == 1          # compared nothing must NOT read as PASS
    rc = br.main(["--baseline", str(base), "--fresh", str(fresh),
                  "--require-paired", "0"])              # explicit opt-out
    assert rc == 0


def test_self_check_mode_and_verdict_file(tmp_path):
    ledger = tmp_path / "rows.jsonl"
    with open(ledger, "w") as f:
        for s in range(2):
            f.write(json.dumps(_row(seed=s)) + "\n")
    verdict_path = tmp_path / "verdict.json"
    rc = br.main(["--self-check", str(ledger),
                  "--verdict", str(verdict_path)])
    assert rc == 0
    v = json.loads(verdict_path.read_text())
    assert v["mode"] == "self-check" and v["ok"]
    assert v["clean_pass"] and v["injection_flagged"]
    assert v["injected"]["regressions"]


def test_gate_mode_cli_and_require_paired(tmp_path):
    base = tmp_path / "base.jsonl"
    fresh = tmp_path / "fresh.jsonl"
    base.write_text(json.dumps(_row(tps=100.0)) + "\n")
    fresh.write_text(json.dumps(_row(tps=50.0)) + "\n")
    verdict_path = tmp_path / "v.json"
    rc = br.main(["--baseline", str(base), "--fresh", str(fresh),
                  "--verdict", str(verdict_path)])
    assert rc == 1
    v = json.loads(verdict_path.read_text())
    assert v["mode"] == "gate" and not v["ok"]
    # a gate that paired nothing must be able to say so loudly
    other = tmp_path / "other.jsonl"
    other.write_text(json.dumps(_row(rows=64)) + "\n")
    rc = br.main(["--baseline", str(base), "--fresh", str(other),
                  "--require-paired", "1"])
    assert rc == 1


def test_committed_ledgers_self_check():
    """The make-check invocation, in-process: the repo's own ledgers
    pass clean and flag the planted loss."""
    root = os.path.join(os.path.dirname(__file__), "..")
    v = br.self_check([os.path.join(root, "serve_r12.jsonl"),
                       os.path.join(root, "decode_spec_r14.jsonl")],
                      br.DEFAULT_METRICS)
    assert v["ok"] and v["clean_pass"] and v["injection_flagged"]
