"""Oracle tests for the scan (prefix-reduction) family — numpy
cumulative reductions as the closed-form expectation, the pattern-oracle
discipline of the reference's drivers (SURVEY.md §4.1)."""

import jax.numpy as jnp
import numpy as np
import pytest

from icikit.parallel import SCAN_ALGORITHMS, scan_reduce
from icikit.utils.mesh import make_mesh, shard_along

_NP_CUM = {"sum": np.cumsum,
           "max": np.maximum.accumulate,
           "min": np.minimum.accumulate}


def _data(p, m, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(-100, 100, size=(p, m)).astype(np.int32)


@pytest.mark.parametrize("algorithm", SCAN_ALGORITHMS)
@pytest.mark.parametrize("op", ["sum", "max", "min"])
def test_inclusive_scan(mesh8, algorithm, op):
    p, m = 8, 16
    data = _data(p, m, seed=1)
    x = shard_along(jnp.asarray(data), mesh8)
    out = np.asarray(scan_reduce(x, mesh8, algorithm=algorithm, op=op))
    np.testing.assert_array_equal(out, _NP_CUM[op](data, axis=0))


@pytest.mark.parametrize("algorithm", SCAN_ALGORITHMS)
def test_exclusive_scan(mesh8, algorithm):
    p, m = 8, 16
    data = _data(p, m, seed=2)
    x = shard_along(jnp.asarray(data), mesh8)
    out = np.asarray(scan_reduce(x, mesh8, algorithm=algorithm,
                                 inclusive=False))
    expected = np.concatenate(
        [np.zeros((1, m), np.int32), np.cumsum(data, axis=0)[:-1]])
    np.testing.assert_array_equal(out, expected)


@pytest.mark.parametrize("algorithm", SCAN_ALGORITHMS)
@pytest.mark.parametrize("op", ["max", "min"])
def test_exclusive_scan_minmax_identity(mesh8, algorithm, op):
    """Device 0 of an exclusive max/min scan holds the op identity."""
    p, m = 8, 4
    data = _data(p, m, seed=3)
    x = shard_along(jnp.asarray(data), mesh8)
    out = np.asarray(scan_reduce(x, mesh8, algorithm=algorithm, op=op,
                                 inclusive=False))
    ident = (np.iinfo(np.int32).min if op == "max"
             else np.iinfo(np.int32).max)
    np.testing.assert_array_equal(out[0], np.full(m, ident, np.int32))
    np.testing.assert_array_equal(out[1:], _NP_CUM[op](data, axis=0)[:-1])


@pytest.mark.parametrize("algorithm", SCAN_ALGORITHMS)
def test_scan_non_pow2(algorithm):
    """Every scan schedule supports any p (partial perms, not XOR)."""
    p, m = 6, 8
    mesh = make_mesh(p)
    data = _data(p, m, seed=4)
    x = shard_along(jnp.asarray(data), mesh)
    out = np.asarray(scan_reduce(x, mesh, algorithm=algorithm))
    np.testing.assert_array_equal(out, np.cumsum(data, axis=0))


@pytest.mark.parametrize("algorithm", SCAN_ALGORITHMS)
def test_scan_float(mesh8, algorithm):
    p, m = 8, 8
    rng = np.random.default_rng(5)
    data = rng.standard_normal((p, m)).astype(np.float32)
    x = shard_along(jnp.asarray(data), mesh8)
    out = np.asarray(scan_reduce(x, mesh8, algorithm=algorithm))
    np.testing.assert_allclose(out, np.cumsum(data, axis=0), rtol=1e-5,
                               atol=1e-5)


def test_scan_p1(mesh1):
    data = _data(1, 8, seed=6)
    x = shard_along(jnp.asarray(data), mesh1)
    np.testing.assert_array_equal(
        np.asarray(scan_reduce(x, mesh1, algorithm="hillis_steele")), data)
    out_ex = np.asarray(scan_reduce(x, mesh1, algorithm="linear",
                                    inclusive=False))
    np.testing.assert_array_equal(out_ex, np.zeros_like(data))


def test_scan_in_registry():
    from icikit.utils.registry import list_algorithms
    assert set(SCAN_ALGORITHMS) == set(list_algorithms("scan"))
