"""Test configuration: simulate an 8-device mesh on CPU.

The reference could only test multi-rank behavior on a real PBS cluster
(SURVEY.md §4.6); here XLA's host-platform device-count simulation makes
"multi-node without a cluster" an actual capability. These env vars must
be set before jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the env may pre-select a TPU
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import pytest  # noqa: E402

# Plugins (jaxtyping) may import jax before this conftest runs, locking in
# env-derived config defaults — override via the config API, which works
# any time before backend initialization.
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # jax < 0.5 has no such option; the XLA_FLAGS fallback above
    # already forced the 8-device host-platform simulation
    pass

# Persistent XLA compilation cache (round 14): the module-boundary
# clear_caches() fixture below bounds memory by dropping compiled
# executables — at the price of recompiling shared programs in every
# later module, which makes the near-full suite compile-bound on this
# CPU image. The on-disk cache turns those recompiles into disk hits
# (within one run AND across runs) while the in-memory profile stays
# bounded. ICIKIT_JAX_CACHE=off disables; any other value overrides
# the cache directory.
_cache_dir = os.environ.get("ICIKIT_JAX_CACHE",
                            "/tmp/icikit_jax_cache")
if _cache_dir != "off":
    try:
        jax.config.update("jax_compilation_cache_dir", _cache_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.1)
    except AttributeError:
        pass    # older jax without the persistent cache: no-op

from icikit.utils.mesh import make_mesh  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running test (subprocess scale points, "
        "big fixtures)")
    config.addinivalue_line(
        "markers", "chaos: fault-injection soak test (worker death, "
        "stragglers, bit-flips, I/O faults; run via `make chaos`)")


@pytest.fixture(scope="session")
def mesh8():
    return make_mesh(8)


@pytest.fixture(scope="session")
def mesh4():
    return make_mesh(4)


@pytest.fixture(scope="session")
def mesh1():
    return make_mesh(1)


@pytest.fixture(scope="session", autouse=True)
def _check_devices():
    assert jax.device_count() >= 8, (
        "expected >= 8 simulated CPU devices; XLA_FLAGS not applied?")


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Drop compiled-program caches at each module boundary: with ~580
    tests in one process the accumulated executables/tracing caches
    drove the XLA:CPU compiler into a segfault near the end of the
    suite (reproducibly, in a test that passes standalone). Costs some
    recompilation; buys a bounded memory profile."""
    yield
    import gc

    jax.clear_caches()
    gc.collect()


_EXIT_STATUS = [0]
_TESTS_RUN = [0]


def pytest_runtest_logreport(report):
    if report.when == "call":
        _TESTS_RUN[0] += 1


@pytest.hookimpl(trylast=True)
def pytest_sessionfinish(session, exitstatus):
    _EXIT_STATUS[0] = int(exitstatus)


@pytest.hookimpl(trylast=True)
def pytest_unconfigure(config):
    """Skip interpreter teardown: with ~580 tests in one process the
    XLA:CPU runtime segfaults on shutdown (exit 139 — and, before
    guard.disarm() restored signal dispositions, the trap handler's
    exit 2 with truncated output — after every test passed). By
    unconfigure the terminal summary has printed; trylast lets other
    plugins' unconfigure finalizers (log files, coverage) complete
    first, then exit with pytest's own status before the faulty
    destructors run. Scoped: small targeted runs (the dev loop) keep
    normal interpreter shutdown — the crash needs the accumulated
    program count of a near-full suite — so genuine teardown
    regressions stay visible outside full-suite runs. Escape hatch:
    ICIKIT_NO_EARLY_EXIT=1 always restores normal shutdown."""
    if os.environ.get("ICIKIT_NO_EARLY_EXIT"):
        return
    if _TESTS_RUN[0] < 200:  # segfault observed only near ~576 programs
        return
    import logging
    import sys

    logging.shutdown()
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(_EXIT_STATUS[0])
