"""Test configuration: simulate an 8-device mesh on CPU.

The reference could only test multi-rank behavior on a real PBS cluster
(SURVEY.md §4.6); here XLA's host-platform device-count simulation makes
"multi-node without a cluster" an actual capability. These env vars must
be set before jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the env may pre-select a TPU
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import pytest  # noqa: E402

# Plugins (jaxtyping) may import jax before this conftest runs, locking in
# env-derived config defaults — override via the config API, which works
# any time before backend initialization.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

from icikit.utils.mesh import make_mesh  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running test (subprocess scale points, "
        "big fixtures)")


@pytest.fixture(scope="session")
def mesh8():
    return make_mesh(8)


@pytest.fixture(scope="session")
def mesh4():
    return make_mesh(4)


@pytest.fixture(scope="session")
def mesh1():
    return make_mesh(1)


@pytest.fixture(scope="session", autouse=True)
def _check_devices():
    assert jax.device_count() >= 8, (
        "expected >= 8 simulated CPU devices; XLA_FLAGS not applied?")
