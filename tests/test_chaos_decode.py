"""Chaos injection sites in the decode path (speculative.py/decode.py)
— the first slice of ROADMAP's "chaos coverage for the remaining
pipelines".

Sites drilled:

- ``decode.prefill``          — greedy/sampled generate dispatch
- ``decode.spec.prefill``     — speculative program dispatch
- ``decode.spec.drafter.*``   — drafter selection (site-named per
                                drafter, so a drill can target the
                                trained head specifically)
- ``decode.spec.verify.stats``— SDC drill on the acceptance-stats
                                readback: corrupt telemetry must skew
                                counters only, never committed tokens
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from icikit import chaos
from icikit.models.transformer import (
    TransformerConfig,
    init_params,
    speculative_generate,
)
from icikit.models.transformer.decode import greedy_generate
from icikit.models.transformer.model import make_model_mesh

CFG = TransformerConfig(vocab=61, d_model=32, n_heads=2, d_head=8,
                        d_ff=64, n_layers=2, max_seq=32,
                        compute_dtype="float32")


def _setup():
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    rng = np.random.default_rng(0)
    pd = jnp.asarray(rng.integers(0, 61, (2, 8)), jnp.int32)
    return mesh, params, pd


def test_decode_prefill_die_site():
    mesh, params, pd = _setup()
    plan = chaos.FaultPlan(schedule={"die:decode.prefill": (0,)})
    with chaos.inject(plan):
        with pytest.raises(chaos.InjectedDeath):
            greedy_generate(params, pd, mesh, CFG, 4)
        # next call: that schedule index is consumed — recovery is
        # a plain retry
        out = greedy_generate(params, pd, mesh, CFG, 4)
    assert out.shape == (2, 12)
    assert plan.fired("die", "decode.prefill") == 1


def test_spec_prefill_and_drafter_die_sites():
    mesh, params, pd = _setup()
    # the first call dies at prefill BEFORE reaching the drafter
    # probe, so the drafter site's call counter is still 0 when the
    # second call gets there
    plan = chaos.FaultPlan(schedule={
        "die:decode.spec.prefill": (0,),
        # the default no-head drafter is "ngram" as of the r11 flip —
        # the drill follows the shipped default's site name
        "die:decode.spec.drafter.ngram": (0,),
    })
    with chaos.inject(plan):
        with pytest.raises(chaos.InjectedDeath):
            speculative_generate(params, pd, mesh, CFG, 4, k=2)
        with pytest.raises(chaos.InjectedDeath):
            # second pass survives prefill, dies at drafter dispatch
            speculative_generate(params, pd, mesh, CFG, 4, k=2)
        out = speculative_generate(params, pd, mesh, CFG, 4, k=2)
    assert out.shape == (2, 12)
    assert plan.fired("die", "decode.spec.*") == 2


def test_spec_drafter_site_is_named_per_drafter():
    """A drill targeting the trained drafter must not fire on shared
    dispatches (and vice versa) — the site name carries the drafter."""
    import dataclasses
    cfg = dataclasses.replace(CFG, draft_head=True, draft_layers=1,
                              draft_rank=4)
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), cfg, mesh)
    rng = np.random.default_rng(0)
    pd = jnp.asarray(rng.integers(0, 61, (2, 8)), jnp.int32)
    plan = chaos.FaultPlan(
        schedule={"die:decode.spec.drafter.trained": (0, 1, 2)})
    with chaos.inject(plan):
        # shared dispatch sails through the trained-only drill
        speculative_generate(params, pd, mesh, cfg, 4, k=2,
                             drafter="shared")
        with pytest.raises(chaos.InjectedDeath):
            speculative_generate(params, pd, mesh, cfg, 4, k=2,
                                 drafter="trained")
    assert plan.fired("die", "decode.spec.drafter.trained") == 1
    assert plan.fired("die", "decode.spec.drafter.shared") == 0


def test_spec_stats_corruption_skews_telemetry_not_tokens():
    """The SDC drill at the stats readback: committed tokens are
    unaffected (they never pass through the stats vector), telemetry
    stays JSON-safe."""
    import json
    mesh, params, pd = _setup()
    base = np.asarray(speculative_generate(params, pd, mesh, CFG, 6,
                                           k=2))
    plan = chaos.FaultPlan(
        schedule={"corrupt:decode.spec.verify.stats": (0,)})
    with chaos.inject(plan):
        out, st = speculative_generate(params, pd, mesh, CFG, 6, k=2,
                                       return_stats=True)
    assert plan.fired("corrupt", "decode.spec.verify.stats") == 1
    np.testing.assert_array_equal(np.asarray(out), base)
    json.dumps(st)   # telemetry must stay serializable even when skewed


def test_spec_delay_sites_fire_without_changing_output():
    mesh, params, pd = _setup()
    base = np.asarray(speculative_generate(params, pd, mesh, CFG, 6,
                                           k=3))
    plan = chaos.FaultPlan(rates={"delay:decode.spec.*": 1.0},
                           delay_s=0.001)
    with chaos.inject(plan):
        out = speculative_generate(params, pd, mesh, CFG, 6, k=3)
    np.testing.assert_array_equal(np.asarray(out), base)
    assert plan.fired("delay", "decode.spec.prefill") == 1
    # default drafter post-r11-flip: ngram
    assert plan.fired("delay", "decode.spec.drafter.ngram") == 1
