"""Tiered KV cache: engine identity pins + tier chaos drills (r16).

The tentpole contract, pinned by outputs rather than construction
claims: spill and restore are **bitwise invisible** to committed
tokens. Every test decodes through the real admission machinery —
eviction pressure spills indexed blocks to the host tier, a later
same-prefix admission swaps them back in through the bounded restore
stream, a restarted engine re-warms from the persistent store — and
every served continuation must equal single-request
``greedy_generate`` exactly, across dp/tp meshes and with quantized
co-batch neighbors.

The failure drills exercise the real detection paths:

- a flipped spilled byte (``corrupt:serve.kv.spill``) fails the
  swap-in digest verify, the content is quarantined from every tier,
  and the request recomputes fresh — burning no retry, with
  co-batched rows bitwise unchanged;
- a store write killed mid-bytes (``die:serve.store.write``) leaves
  a torn file that rewarm skips (and removes) instead of trusting;
- a never-firing armed plan leaves tiered traffic bit-identical to
  the unarmed baseline (the probe sites are free).
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from icikit import chaos
from icikit.models.transformer import (
    TransformerConfig,
    greedy_generate,
    init_params,
)
from icikit.models.transformer.model import make_model_mesh
from icikit.serve import Engine, PrefixStore, RequestQueue, ServeConfig

CFG = TransformerConfig(vocab=61, d_model=32, n_heads=2, d_head=8,
                        d_ff=64, n_layers=2, max_seq=64,
                        compute_dtype="float32")

SV = dict(max_rows=2, block_size=4, n_blocks=8, max_prompt=16,
          max_new=16, host_cache_blocks=32)


def _setup(mesh=None, seed=3, n_new=10):
    mesh = mesh or make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    rng = np.random.default_rng(seed)
    target = rng.integers(0, CFG.vocab, (8,)).astype(np.int32)
    fillers = [rng.integers(0, CFG.vocab, (8,)).astype(np.int32)
               for _ in range(3)]
    # the baseline decodes on a dp=1 mesh (a b=1 prompt cannot shard
    # over dp=2); greedy tokens are mesh-invariant, which the serving
    # identity pins elsewhere already rely on
    m1 = make_model_mesh(dp=1, tp=1, sp=1)
    p1 = init_params(jax.random.key(0), CFG, m1)
    base = np.asarray(greedy_generate(
        p1, jnp.asarray(target)[None], m1, CFG, n_new))[0, 8:]
    return mesh, params, target, fillers, base


def _spill_target(eng, target, fillers, n_new=10):
    """Serve the target once (its prefix registers), then fill the
    tiny pool with other traffic until the target's blocks are
    EVICTED into the spill tier — the deterministic pressure recipe
    every test below builds on."""
    eng.submit(target, n_new)
    eng.run()
    for p in fillers:
        eng.submit(p, n_new)
        eng.run()
    from icikit.serve.kvpool import block_hashes
    hs = block_hashes(target, eng.serve.block_size)
    a = eng.pool.allocators[0]
    assert any(a.spilled(h) for h in hs), \
        "pressure recipe failed to spill the target's chain"
    return hs


@pytest.mark.parametrize("dp,tp", [(1, 1), (2, 1), (1, 2)])
def test_hit_on_spilled_chain_is_token_identical(dp, tp):
    """An admission landing on a fully spilled chain restores it and
    serves tokens bitwise equal to single-request generate, with the
    restore accounted as a (spill) hit."""
    mesh = make_model_mesh(dp=dp, tp=tp, sp=1)
    mesh, params, target, fillers, base = _setup(mesh)
    sv = dict(SV, max_rows=2 * dp) if dp > 1 else dict(SV)
    eng = Engine(params, mesh, CFG, ServeConfig(**sv))
    _spill_target(eng, target, fillers)
    rid = eng.submit(target, 10)
    eng.run()
    req = eng.queue.request(rid)
    assert req.state == "done" and req.attempts == 1
    np.testing.assert_array_equal(np.asarray(req.tokens), base)
    st = eng.prefix_stats()
    assert st["spill_hits"] >= 1 and st["restores"] >= 1
    assert st["restores_host"] == st["restores"]
    assert st["spill_hit_tokens"] > 0
    assert req.prefix_hit_tokens == 7     # full hit: s-1 recompute


def test_partial_spill_mixes_device_and_host_tiers():
    """Half the chain resident, half spilled: the admission shares
    the device prefix and restores only the spilled remainder —
    still token-identical."""
    mesh, params, target, fillers, base = _setup()
    eng = Engine(params, mesh, CFG, ServeConfig(**SV))
    hs = _spill_target(eng, target, fillers)
    # revive the ROOT block onto the device (cached) while the deeper
    # block stays spilled: restore root into a temp owner and release
    out = eng.pool.restore_block("__pin", 0, hs[0])
    assert out is not None
    eng.pool.release("__pin", 0)
    a = eng.pool.allocators[0]
    assert a.indexed(hs[0]) is not None and a.spilled(hs[1])
    rid = eng.submit(target, 10)
    eng.run()
    req = eng.queue.request(rid)
    np.testing.assert_array_equal(np.asarray(req.tokens), base)
    st = eng.prefix_stats()
    assert st["hits"] >= 1 and st["restores"] >= 1


def test_rewarm_from_store_then_hit_is_token_identical(tmp_path):
    """The restart story: engine 1 persists its sealed blocks at
    drain; a FRESH engine over the same store rewarms the queued
    prompts' chains from disk (the RequestQueue.pending_prompts
    hook) and serves them token-identically, with the store as the
    restore source."""
    mesh, params, target, fillers, base = _setup()
    sv = ServeConfig(**SV, store_dir=str(tmp_path / "store"))
    eng1 = Engine(params, mesh, CFG, sv)
    eng1.submit(target, 10)
    eng1.run()                     # drain flush persists the chain
    assert eng1.pool.store.n_blocks() >= 2
    # restart: fresh engine, fresh pool, same store
    q2 = RequestQueue()
    eng2 = Engine(params, mesh, CFG, sv, queue=q2)
    rid = eng2.submit(target, 10)
    n = eng2.rewarm()              # defaults to pending_prompts()
    assert n >= 2                  # the prompt's two full blocks
    eng2.run()
    req = q2.request(rid)
    assert req.state == "done"
    np.testing.assert_array_equal(np.asarray(req.tokens), base)
    # rewarmed blocks were CACHED: the admission hit them on-device
    assert eng2.prefix_stats()["hits"] >= 1


def test_demand_paging_from_store_without_rewarm(tmp_path):
    """No eager rewarm: the admission path's tier lookup pulls the
    persisted chain from disk on demand — same identity, restores
    sourced from the store."""
    mesh, params, target, fillers, base = _setup()
    sv = ServeConfig(**SV, store_dir=str(tmp_path / "store"))
    eng1 = Engine(params, mesh, CFG, sv)
    eng1.submit(target, 10)
    eng1.run()
    q2 = RequestQueue()
    eng2 = Engine(params, mesh, CFG, sv, queue=q2)
    rid = eng2.submit(target, 10)
    eng2.run()
    req = q2.request(rid)
    np.testing.assert_array_equal(np.asarray(req.tokens), base)
    st = eng2.prefix_stats()
    assert st["restores_store"] >= 1 and st["spill_hits"] >= 1


def test_mixed_engine_fp_restore_with_q8_cobatch():
    """Containment: an fp row served through the restore path
    co-batched with an int8 row — the fp tokens stay bitwise
    generate's (the tier never touches the q8 arenas of a mixed
    pool)."""
    mesh, params, target, fillers, base = _setup()
    sv = ServeConfig(**dict(SV, n_blocks=12), kv_quant="mixed")
    eng = Engine(params, mesh, CFG, sv)
    _spill_target(eng, target, fillers)
    r_fp = eng.submit(target, 10)
    r_q8 = eng.submit(fillers[0], 10, quant=True)
    eng.run()
    np.testing.assert_array_equal(
        np.asarray(eng.queue.request(r_fp).tokens), base)
    assert eng.queue.request(r_q8).state == "done"
    assert eng.prefix_stats()["restores"] >= 1


def test_spilled_byte_flip_quarantined_and_recomputed():
    """The tier SDC drill: a flipped byte in the spilled payload
    fails the swap-in digest verify, the content is quarantined from
    the host tier, and the request recomputes fresh — same tokens,
    SAME attempt (no retry burned), co-batched row bitwise
    unchanged."""
    mesh, params, target, fillers, base = _setup()
    other = np.asarray([7, 11, 13, 17, 19, 23, 29, 31], np.int32)
    other_base = np.asarray(greedy_generate(
        params, jnp.asarray(other)[None], mesh, CFG, 10))[0, 8:]
    eng = Engine(params, mesh, CFG, ServeConfig(**SV))
    hs = _spill_target(eng, target, fillers)
    rid = eng.submit(target, 10)
    r_other = eng.submit(other, 10)
    plan = chaos.FaultPlan(schedule={"corrupt:serve.kv.spill": (0,)})
    with chaos.inject(plan):
        eng.run()
    assert plan.fired("corrupt", "serve.kv.spill") == 1
    req = eng.queue.request(rid)
    assert req.state == "done" and req.attempts == 1
    np.testing.assert_array_equal(np.asarray(req.tokens), base)
    np.testing.assert_array_equal(
        np.asarray(eng.queue.request(r_other).tokens), other_base)
    # the corrupt content left the tier (quarantined, not retryable)
    a = eng.pool.allocators[0]
    assert not a.spilled(hs[0])
    st = eng.prefix_stats()
    assert st["restores"] == 0         # nothing corrupt was trusted


def test_torn_store_write_skipped_at_rewarm(tmp_path):
    """The disk-tier die drill: a store write killed mid-bytes leaves
    a torn file; a restarted engine's rewarm SKIPS it (validation
    quarantine) and recomputes — tokens still identical."""
    mesh, params, target, fillers, base = _setup()
    sv = ServeConfig(**SV, store_dir=str(tmp_path / "store"))
    eng1 = Engine(params, mesh, CFG, sv)
    eng1.submit(target, 10)
    plan = chaos.FaultPlan(schedule={"die:serve.store.write": (0,)})
    with chaos.inject(plan):
        with pytest.raises(chaos.InjectedDeath):
            eng1.run()         # dies inside the drain flush
    assert plan.fired("die", "serve.store.write") == 1
    store = PrefixStore(str(tmp_path / "store"))
    n_files = store.n_blocks()
    assert n_files >= 1        # the torn file is on disk
    torn = [p.stem for p in sorted(
        (tmp_path / "store").glob("*.npz"))]
    # the torn entry fails validation and is removed; intact ones
    # (written before the kill) still load
    loaded = [store.get(h) for h in torn]
    assert any(rec is None for rec in loaded)
    assert store.n_quarantined >= 1
    # a fresh engine over the same store serves correctly regardless
    q2 = RequestQueue()
    eng2 = Engine(params, mesh, CFG, sv, queue=q2)
    rid = eng2.submit(target, 10)
    eng2.rewarm()
    eng2.run()
    np.testing.assert_array_equal(
        np.asarray(q2.request(rid).tokens), base)


def test_store_read_corruption_quarantined(tmp_path):
    """The disk-tier SDC drill: a flipped persisted byte (injected on
    the read path, after the bytes parsed) fails the swap-in verify;
    the file is quarantined and the request recomputes fresh —
    identical tokens, no retry burned."""
    mesh, params, target, fillers, base = _setup()
    sv = ServeConfig(**SV, store_dir=str(tmp_path / "store"))
    eng1 = Engine(params, mesh, CFG, sv)
    eng1.submit(target, 10)
    eng1.run()
    n0 = eng1.pool.store.n_blocks()
    assert n0 >= 2
    q2 = RequestQueue()
    eng2 = Engine(params, mesh, CFG, sv, queue=q2)
    rid = eng2.submit(target, 10)
    plan = chaos.FaultPlan(schedule={"corrupt:serve.store.read": (0,)})
    with chaos.inject(plan):
        eng2.run()
    assert plan.fired("corrupt", "serve.store.read") == 1
    req = q2.request(rid)
    assert req.state == "done" and req.attempts == 1
    np.testing.assert_array_equal(np.asarray(req.tokens), base)
    assert eng2.pool.store.n_quarantined >= 1


def test_clean_armed_tiered_run_identical():
    """A never-firing plan over tiered traffic (spills, restores,
    store writes all live) leaves outputs bit-identical to the
    unarmed baseline — the tier probe sites are free."""
    mesh, params, target, fillers, base = _setup()
    eng = Engine(params, mesh, CFG, ServeConfig(**SV))
    _spill_target(eng, target, fillers)
    rid = eng.submit(target, 10)
    plan = chaos.FaultPlan(rates={"die:serve.kv.*": 0.0,
                                  "delay:serve.store.*": 0.0})
    with chaos.inject(plan):
        eng.run()
    assert plan.log == []
    np.testing.assert_array_equal(
        np.asarray(eng.queue.request(rid).tokens), base)


def test_dead_engine_mid_restore_reissues_token_identically():
    """An engine dying AT the restore boundary (die:serve.kv.restore)
    abandons its lease; a second engine completes the request
    token-identically — restores carry no engine state."""
    mesh, params, target, fillers, base = _setup()
    q = RequestQueue(lease_s=0.05)
    eng1 = Engine(params, mesh, CFG, ServeConfig(**SV), queue=q)
    _spill_target(eng1, target, fillers)
    rid = eng1.submit(target, 10)
    plan = chaos.FaultPlan(schedule={"die:serve.kv.restore": (0,)})
    with chaos.inject(plan):
        with pytest.raises(chaos.InjectedDeath):
            eng1.run()
        time.sleep(0.06)
        eng2 = Engine(params, mesh, CFG, ServeConfig(**SV), queue=q)
        eng2.run()
    req = q.request(rid)
    assert req.state == "done" and req.attempts == 2
    np.testing.assert_array_equal(np.asarray(req.tokens), base)


def test_tiers_require_prefix_cache():
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    with pytest.raises(ValueError, match="prefix_cache"):
        Engine(params, mesh, CFG,
               ServeConfig(**dict(SV, prefix_cache=False)))


def test_prefix_store_roundtrip_and_validation(tmp_path):
    """PrefixStore unit surface: put/get/has round trip, content
    addressing (duplicate put is a no-op), format validation, torn
    file quarantine."""
    store = PrefixStore(tmp_path / "s")
    arrays = [np.arange(12, dtype=np.float32).reshape(3, 4),
              np.ones((2, 2), np.float32)]
    assert store.put("abc", "fp", "d1gest", arrays)
    assert not store.put("abc", "fp", "d1gest", arrays)  # LWW no-op
    assert store.has("abc") and not store.has("zzz")
    side, digest, back = store.get("abc")
    assert side == "fp" and digest == "d1gest"
    for a, b in zip(arrays, back):
        np.testing.assert_array_equal(a, b)
    assert store.n_blocks() == 1 and store.nbytes() > 0
    # torn file: truncate -> get() quarantines (None + file removed)
    path = store._path("abc")
    path.write_bytes(path.read_bytes()[:20])
    assert store.get("abc") is None
    assert not store.has("abc") and store.n_quarantined == 1
