"""Hybrid-mesh (ICI x DCN) and hierarchical-collective oracle tests.

The 8 simulated CPU devices stand in for (dcn_size x ici_size) hybrid
topologies, exercising the multi-host schedules without a pod —
SURVEY.md §4.6's "multi-node without a cluster" capability applied to
the two-tier fabric.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from icikit.parallel.multihost import (
    hierarchical_all_reduce,
    init_distributed,
    make_hybrid_mesh,
    process_info,
)
from icikit.utils.mesh import shard_along


def _hybrid_data(mesh, m, seed=0):
    p = mesh.devices.size
    rng = np.random.default_rng(seed)
    data = rng.integers(-100, 100, size=(p, m)).astype(np.int32)
    x = shard_along(jnp.asarray(data), mesh, axis_name=("dcn", "p"))
    return data, x


def test_make_hybrid_mesh_shapes():
    mesh = make_hybrid_mesh(dcn_size=2)
    assert mesh.shape == {"dcn": 2, "p": 4}
    mesh = make_hybrid_mesh(dcn_size=4, ici_size=2)
    assert mesh.shape == {"dcn": 4, "p": 2}
    mesh = make_hybrid_mesh()  # single process: dcn collapses to 1
    assert mesh.shape["dcn"] == 1


def test_make_hybrid_mesh_validates():
    with pytest.raises(ValueError):
        make_hybrid_mesh(dcn_size=3)  # 8 % 3 != 0
    with pytest.raises(ValueError):
        make_hybrid_mesh(dcn_size=4, ici_size=4)  # 16 > 8 devices


@pytest.mark.parametrize("dcn,ici", [(2, 4), (4, 2), (2, 2), (1, 8)])
@pytest.mark.parametrize("ici_algorithm", ["ring", "recursive_doubling",
                                           "xla"])
def test_hierarchical_allreduce_sum(dcn, ici, ici_algorithm):
    mesh = make_hybrid_mesh(dcn_size=dcn, ici_size=ici)
    m = 4 * ici  # divisible by p_ici
    data, x = _hybrid_data(mesh, m)
    out = np.asarray(hierarchical_all_reduce(
        x, mesh, ici_algorithm=ici_algorithm))
    expected = data.sum(axis=0)
    for d in range(dcn * ici):
        np.testing.assert_array_equal(out[d], expected)


@pytest.mark.parametrize("dcn_algorithm", ["ring", "recursive_doubling",
                                           "xla"])
def test_hierarchical_allreduce_dcn_algorithms(dcn_algorithm):
    mesh = make_hybrid_mesh(dcn_size=2, ici_size=4)
    data, x = _hybrid_data(mesh, 16, seed=1)
    out = np.asarray(hierarchical_all_reduce(
        x, mesh, dcn_algorithm=dcn_algorithm))
    expected = data.sum(axis=0)
    for d in range(8):
        np.testing.assert_array_equal(out[d], expected)


@pytest.mark.parametrize("op,npop", [("max", np.max), ("min", np.min)])
def test_hierarchical_allreduce_minmax(op, npop):
    mesh = make_hybrid_mesh(dcn_size=2, ici_size=4)
    data, x = _hybrid_data(mesh, 8, seed=2)
    out = np.asarray(hierarchical_all_reduce(x, mesh, op=op))
    expected = npop(data, axis=0)
    for d in range(8):
        np.testing.assert_array_equal(out[d], expected)


def test_hierarchical_allreduce_rejects_indivisible():
    mesh = make_hybrid_mesh(dcn_size=2, ici_size=4)
    data, x = _hybrid_data(mesh, 8)
    with pytest.raises(ValueError):
        hierarchical_all_reduce(x[:, :6], mesh)  # 6 % 4 != 0


def test_init_distributed_noop_single_process(monkeypatch):
    for v in ("COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS",
              "MEGASCALE_COORDINATOR_ADDRESS"):
        monkeypatch.delenv(v, raising=False)
    assert init_distributed() is False  # no cluster detectable: no-op


def test_process_info_single_process():
    idx, count, local = process_info()
    assert idx == 0 and count == 1 and local >= 8


# --------------------------------------------------------------- new tiers


@pytest.mark.parametrize("dcn,ici", [(2, 4), (4, 2), (1, 8)])
@pytest.mark.parametrize("algorithm", ["ring", "xla"])
def test_hierarchical_all_gather(dcn, ici, algorithm):
    from icikit.parallel.multihost import hierarchical_all_gather
    mesh = make_hybrid_mesh(dcn_size=dcn, ici_size=ici)
    data, x = _hybrid_data(mesh, 8, seed=3)
    out = np.asarray(hierarchical_all_gather(
        x, mesh, dcn_algorithm=algorithm, ici_algorithm=algorithm))
    assert out.shape == (dcn * ici, dcn * ici, 8)
    for d in range(dcn * ici):
        np.testing.assert_array_equal(out[d], data)


@pytest.mark.parametrize("dcn,ici", [(2, 4), (4, 2)])
@pytest.mark.parametrize("op,npop", [("sum", np.sum), ("max", np.max)])
def test_hierarchical_reduce_scatter(dcn, ici, op, npop):
    from icikit.parallel.multihost import (
        hier_chunk_index,
        hierarchical_reduce_scatter,
    )
    mesh = make_hybrid_mesh(dcn_size=dcn, ici_size=ici)
    p = dcn * ici
    m = 2 * p
    data, x = _hybrid_data(mesh, m, seed=4)
    out = np.asarray(hierarchical_reduce_scatter(x, mesh, op=op))
    total = npop(data, axis=0).reshape(p, m // p)
    chunk_of = hier_chunk_index(mesh)
    for d in range(p):
        np.testing.assert_array_equal(out[d], total[chunk_of[d]])


def test_hierarchical_reduce_scatter_validates():
    from icikit.parallel.multihost import hierarchical_reduce_scatter
    mesh = make_hybrid_mesh(dcn_size=2, ici_size=4)
    data, x = _hybrid_data(mesh, 8, seed=5)
    with pytest.raises(ValueError):
        hierarchical_reduce_scatter(x[:, :6], mesh)  # 6 % 8 != 0


@pytest.mark.parametrize("dcn,ici", [(2, 4), (4, 2), (2, 2)])
def test_hierarchical_all_to_all(dcn, ici):
    from icikit.parallel.multihost import hierarchical_all_to_all
    mesh = make_hybrid_mesh(dcn_size=dcn, ici_size=ici)
    p = dcn * ici
    rng = np.random.default_rng(6)
    data = rng.integers(-100, 100, size=(p, p, 4)).astype(np.int32)
    x = shard_along(jnp.asarray(data), mesh, axis_name=("dcn", "p"))
    out = np.asarray(hierarchical_all_to_all(x, mesh))
    np.testing.assert_array_equal(out, data.swapaxes(0, 1))


def test_hierarchical_all_to_all_handrolled_carriers():
    from icikit.parallel.multihost import hierarchical_all_to_all
    mesh = make_hybrid_mesh(dcn_size=2, ici_size=4)
    p = 8
    rng = np.random.default_rng(7)
    data = rng.integers(-100, 100, size=(p, p, 4)).astype(np.int32)
    x = shard_along(jnp.asarray(data), mesh, axis_name=("dcn", "p"))
    out = np.asarray(hierarchical_all_to_all(
        x, mesh, ici_algorithm="hypercube", dcn_algorithm="wraparound"))
    np.testing.assert_array_equal(out, data.swapaxes(0, 1))


@pytest.mark.slow
def test_real_two_process_bringup():
    """The actual ``mpirun`` analog: TWO OS processes (4 simulated CPU
    devices each) do the ``jax.distributed`` coordinator handshake,
    build the hybrid mesh across the process boundary, and run
    hierarchical + flat collectives whose messages really cross
    processes (gloo). Reference: ``Communication/Data/sub.sh:9-15``.
    Skips when the coordinator port cannot be claimed (busy CI host).
    """
    import os
    import subprocess
    import sys
    from pathlib import Path

    import jax

    from icikit.utils.net import PORT_RACE_SIGS, free_port

    repo = Path(__file__).resolve().parents[1]
    worker = Path(__file__).resolve().parent / "multihost_worker.py"

    if not hasattr(jax, "distributed") or \
            not hasattr(jax.distributed, "initialize"):
        pytest.skip("this jax has no distributed runtime "
                    "(jax.distributed.initialize missing)")

    def _free_port() -> int:
        """The shared hardened helper (icikit.utils.net — claim with
        SO_REUSEADDR then release, so the coordinator can rebind the
        port immediately); an unbindable host maps to a skip here."""
        try:
            return free_port()
        except OSError as e:  # pragma: no cover
            pytest.skip(f"cannot bind a local port: {e}")

    env = dict(os.environ)
    keep = [x for x in env.get("PYTHONPATH", "").split(os.pathsep)
            if x and not os.path.exists(os.path.join(x, "sitecustomize.py"))]
    env["PYTHONPATH"] = os.pathsep.join([str(repo)] + keep)
    env.pop("XLA_FLAGS", None)  # worker sets its own device count (4)

    # Port races are transient: retry the whole bring-up on a FRESH
    # free port instead of skipping on the first collision — a skip is
    # only honest once the failure mode is environmental, not a race
    # this loop can win.
    PORT_SIGS = PORT_RACE_SIGS
    UNAVAILABLE_SIGS = (
        "UNAVAILABLE", "DEADLINE_EXCEEDED",
        "distributed runtime is not available",
        # this jaxlib build ships no CPU cross-process collectives
        # (gloo absent): bring-up is structurally impossible, not flaky
        "Multiprocess computations aren't implemented",
    )
    outs = []
    for attempt in range(3):
        port = _free_port()
        procs = [subprocess.Popen(
            [sys.executable, str(worker), str(port), str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=repo, env=env) for i in range(2)]
        try:
            outs = [p.communicate(timeout=600)[0] for p in procs]
        except subprocess.TimeoutExpired:  # pragma: no cover
            for p in procs:
                p.kill()
            pytest.skip("2-process bring-up timed out (loaded host)")
        if all(p.returncode == 0 for p in procs):
            break
        joined = "\n".join(outs)
        if any(sig in joined for sig in PORT_SIGS):
            continue  # pragma: no cover - fresh port, try again
        if any(sig in joined for sig in UNAVAILABLE_SIGS):
            pytest.skip("distributed bring-up unavailable on this host "
                        f"({next(s for s in UNAVAILABLE_SIGS if s in joined)})"
                        )  # pragma: no cover
        break  # a real failure: fall through to the assertions
    else:  # pragma: no cover - three straight port races
        pytest.skip("coordinator port kept colliding across 3 fresh "
                    "ports (busy host)")
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]
        assert "WORKER_OK" in out
