"""Hybrid-mesh (ICI x DCN) and hierarchical-collective oracle tests.

The 8 simulated CPU devices stand in for (dcn_size x ici_size) hybrid
topologies, exercising the multi-host schedules without a pod —
SURVEY.md §4.6's "multi-node without a cluster" capability applied to
the two-tier fabric.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from icikit.parallel.multihost import (
    hierarchical_all_reduce,
    init_distributed,
    make_hybrid_mesh,
    process_info,
)
from icikit.utils.mesh import shard_along


def _hybrid_data(mesh, m, seed=0):
    p = mesh.devices.size
    rng = np.random.default_rng(seed)
    data = rng.integers(-100, 100, size=(p, m)).astype(np.int32)
    x = shard_along(jnp.asarray(data), mesh, axis_name=("dcn", "p"))
    return data, x


def test_make_hybrid_mesh_shapes():
    mesh = make_hybrid_mesh(dcn_size=2)
    assert mesh.shape == {"dcn": 2, "p": 4}
    mesh = make_hybrid_mesh(dcn_size=4, ici_size=2)
    assert mesh.shape == {"dcn": 4, "p": 2}
    mesh = make_hybrid_mesh()  # single process: dcn collapses to 1
    assert mesh.shape["dcn"] == 1


def test_make_hybrid_mesh_validates():
    with pytest.raises(ValueError):
        make_hybrid_mesh(dcn_size=3)  # 8 % 3 != 0
    with pytest.raises(ValueError):
        make_hybrid_mesh(dcn_size=4, ici_size=4)  # 16 > 8 devices


@pytest.mark.parametrize("dcn,ici", [(2, 4), (4, 2), (2, 2), (1, 8)])
@pytest.mark.parametrize("ici_algorithm", ["ring", "recursive_doubling",
                                           "xla"])
def test_hierarchical_allreduce_sum(dcn, ici, ici_algorithm):
    mesh = make_hybrid_mesh(dcn_size=dcn, ici_size=ici)
    m = 4 * ici  # divisible by p_ici
    data, x = _hybrid_data(mesh, m)
    out = np.asarray(hierarchical_all_reduce(
        x, mesh, ici_algorithm=ici_algorithm))
    expected = data.sum(axis=0)
    for d in range(dcn * ici):
        np.testing.assert_array_equal(out[d], expected)


@pytest.mark.parametrize("dcn_algorithm", ["ring", "recursive_doubling",
                                           "xla"])
def test_hierarchical_allreduce_dcn_algorithms(dcn_algorithm):
    mesh = make_hybrid_mesh(dcn_size=2, ici_size=4)
    data, x = _hybrid_data(mesh, 16, seed=1)
    out = np.asarray(hierarchical_all_reduce(
        x, mesh, dcn_algorithm=dcn_algorithm))
    expected = data.sum(axis=0)
    for d in range(8):
        np.testing.assert_array_equal(out[d], expected)


@pytest.mark.parametrize("op,npop", [("max", np.max), ("min", np.min)])
def test_hierarchical_allreduce_minmax(op, npop):
    mesh = make_hybrid_mesh(dcn_size=2, ici_size=4)
    data, x = _hybrid_data(mesh, 8, seed=2)
    out = np.asarray(hierarchical_all_reduce(x, mesh, op=op))
    expected = npop(data, axis=0)
    for d in range(8):
        np.testing.assert_array_equal(out[d], expected)


def test_hierarchical_allreduce_rejects_indivisible():
    mesh = make_hybrid_mesh(dcn_size=2, ici_size=4)
    data, x = _hybrid_data(mesh, 8)
    with pytest.raises(ValueError):
        hierarchical_all_reduce(x[:, :6], mesh)  # 6 % 4 != 0


def test_init_distributed_noop_single_process(monkeypatch):
    for v in ("COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS",
              "MEGASCALE_COORDINATOR_ADDRESS"):
        monkeypatch.delenv(v, raising=False)
    assert init_distributed() is False  # no cluster detectable: no-op


def test_process_info_single_process():
    idx, count, local = process_info()
    assert idx == 0 and count == 1 and local >= 8
