"""Trainer CLI: loss decreases on the Markov corpus, checkpointing
resumes at the saved step, and the JSON log stream is well-formed."""

import json

import pytest

from icikit.models.transformer.train import make_markov_sampler, train


def _run(capsys, *extra):
    argv = ["--steps", "6", "--batch", "4", "--vocab", "64",
            "--d-model", "32", "--n-heads", "4", "--d-head", "8",
            "--d-ff", "64", "--n-layers", "1", "--seq", "32",
            "--compute-dtype", "float32", "--log-every", "3",
            "--sample-tokens", "4", *extra]
    assert train(argv) == 0
    return [json.loads(line) for line in
            capsys.readouterr().out.strip().splitlines()]


def test_markov_sampler_deterministic():
    import numpy as np
    s = make_markov_sampler(64, seed=0)
    a = s(1, 2, 16)
    b = s(1, 2, 16)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, s(2, 2, 16))
    assert a.shape == (2, 17)
    assert ((a >= 0) & (a < 64)).all()


def test_markov_native_matches_python_fallback():
    import numpy as np
    from icikit import native
    if not native.available():
        import pytest
        pytest.skip(native.build_error() or "no native runtime")
    a = native.markov_fill(61, 4, 5, 9, 6, 24)
    b = native._markov_fill_py(61, 4, 5, 9, 6, 24,
                               np.empty((6, 25), np.int32))
    np.testing.assert_array_equal(a, b)


def test_loss_drops_and_sample_emitted(capsys):
    # vocab 16: the 256-context transition table is small enough to
    # learn from 30 x 128 tokens; the run is seed-deterministic
    recs = _run(capsys, "--dp", "2", "--tp", "2", "--lr", "1e-2",
                "--vocab", "16", "--steps", "30", "--log-every", "10")
    losses = [r["loss"] for r in recs if "loss" in r]
    assert len(losses) >= 3
    assert losses[-1] < losses[0] - 0.05          # decreasing trend
    assert losses[-1] < 2.77                      # below uniform ln(16)
    sample = [r for r in recs if r.get("event") == "sample"]
    assert sample and len(sample[0]["tokens"]) == 8 + 4


def test_checkpoint_resume(tmp_path, capsys):
    ckpt = str(tmp_path / "run")
    _run(capsys, "--ckpt-dir", ckpt, "--ckpt-every", "3")
    recs = _run(capsys, "--ckpt-dir", ckpt, "--ckpt-every", "3",
                "--steps", "9")
    resumed = [r for r in recs if r.get("event") == "resumed"]
    assert resumed and resumed[0]["step"] == 6
    steps = [r["step"] for r in recs if "step" in r and "loss" in r]
    assert steps and steps[0] > 6 and steps[-1] == 9


def test_bf16_moments_convergence_parity():
    """The r5 bf16-moment FusedAdam must *converge* like fp32 moments,
    not just match early steps: 300 steps on the learnable Markov
    corpus, comparing the tail-averaged loss. This is the numerics pin
    for the optimizer-stream structural route — storage rounding of
    the EMAs must not stall or destabilize training (the known bf16-
    EMA hazard: (1−b2)·g² increments below bf16 resolution get lost)."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from icikit.models.transformer import (
        FusedAdam, TransformerConfig, init_params, make_train_step)
    from icikit.models.transformer.model import make_model_mesh

    cfg = TransformerConfig(vocab=16, d_model=32, n_heads=4, d_head=8,
                            d_ff=64, n_layers=1, max_seq=32,
                            compute_dtype="float32")
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    sampler = make_markov_sampler(16, seed=0)

    def run(tx, steps=300):
        params = init_params(jax.random.key(0), cfg, mesh)
        _, step = make_train_step(mesh, cfg, tx)
        st = tx.init(params)
        losses = []
        for i in range(steps):
            batch = jnp.asarray(sampler(i, 4, 32))
            tok, tgt = batch[:, :-1], batch[:, 1:]
            params, st, loss = step(params, st, tok, tgt)
            losses.append(float(loss))
        return np.asarray(losses)

    l32 = run(FusedAdam(1e-2))
    l16 = run(FusedAdam(1e-2, mu_dtype=jnp.bfloat16,
                        nu_dtype=jnp.bfloat16))
    tail32, tail16 = l32[-30:].mean(), l16[-30:].mean()
    # both learn (below the uniform baseline ln(16) = 2.77, and below
    # their own start)…
    assert tail32 < 2.75 and tail16 < 2.75
    assert tail32 < l32[0] and tail16 < l16[0]
    # …and to the same loss within a tight margin (measured 2026-07-31:
    # 2.6753 vs 2.6743 — the trajectories track almost step-for-step)
    assert abs(tail16 - tail32) < 0.02 * tail32


def test_watchdog_flag_smoke(capsys):
    # arm a generous watchdog; the run finishes inside it and disarms
    # on its own before returning
    import signal
    recs = _run(capsys, "--watchdog", "600")
    assert any("loss" in r for r in recs)
    armed = [r for r in recs if r.get("event") == "watchdog_armed"]
    assert armed and armed[0]["timeout_s"] == 600
    assert signal.alarm(0) == 0  # train() already disarmed


def test_watchdog_env_var_arms_without_flag(monkeypatch, capsys):
    # ICIKIT_WATCHDOG_S must reach runs launched with no --watchdog at
    # all — the batch-queue budget knob needs no CLI edit
    import signal
    monkeypatch.setenv("ICIKIT_WATCHDOG_S", "700")
    recs = _run(capsys)
    armed = [r for r in recs if r.get("event") == "watchdog_armed"]
    assert armed and armed[0]["timeout_s"] == 700
    assert signal.alarm(0) == 0  # disarmed on the way out


def test_watchdog_explicit_zero_beats_env(monkeypatch, capsys):
    monkeypatch.setenv("ICIKIT_WATCHDOG_S", "700")
    recs = _run(capsys, "--watchdog", "0")
    assert not any(r.get("event") == "watchdog_armed" for r in recs)


def test_sample_skipped_when_no_room(capsys):
    recs = _run(capsys, "--sample-tokens", "100")  # seq=32, prompt=8
    samples = [r for r in recs if r.get("event") == "sample"]
    assert samples and len(samples[0]["tokens"]) == 8 + 24  # clamped


def test_device_guard_step_skips_nonfinite_on_device():
    """make_train_step(guard="device"): the fused isfinite reduction
    skips a poisoned update ON DEVICE — params and optimizer state
    hold bit-for-bit, ok comes back False — with no host inspection
    of the loss anywhere in the loop."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from icikit.models.transformer import TransformerConfig, init_params
    from icikit.models.transformer.model import (make_model_mesh,
                                                 make_train_step)

    cfg = TransformerConfig(vocab=32, d_model=32, n_heads=2, d_head=16,
                            d_ff=64, n_layers=1, max_seq=16,
                            compute_dtype="float32")
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), cfg, mesh)
    _, step = make_train_step(mesh, cfg, optax.adam(1e-3),
                              guard="device")
    rng = np.random.default_rng(0)
    tok = jnp.asarray(rng.integers(0, 32, (2, 16)), jnp.int32)
    tgt = jnp.asarray(rng.integers(0, 32, (2, 16)), jnp.int32)
    opt_state = optax.adam(1e-3).init(params)

    # clean step: ok, params move
    p1, st1, loss, ok = step(params, opt_state, tok, tgt)
    assert bool(np.asarray(ok))
    assert not np.array_equal(np.asarray(p1["w1"]),
                              np.asarray(params["w1"]))

    # poisoned params -> non-finite grads -> on-device skip
    bad = dict(params)
    bad["w1"] = bad["w1"].at[0, 0, 0].set(jnp.nan)
    p2, st2, loss2, ok2 = step(bad, opt_state, tok, tgt)
    assert not bool(np.asarray(ok2))
    for k in bad:
        np.testing.assert_array_equal(np.asarray(p2[k]),
                                      np.asarray(bad[k]))
    for a, b in zip(jax.tree.leaves(st2), jax.tree.leaves(opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_device_guard_mode_drill(capsys):
    """Trainer --guard-mode device under the chaos NaN drill: the
    anomaly/rollback events land at the next logging fence (with
    their original step numbers) and the run recovers finite."""
    import numpy as np

    from icikit import chaos

    plan = chaos.FaultPlan(schedule={"corrupt:train.loss": (3, 4)},
                           corrupt_mode="nan")
    with chaos.inject(plan):
        recs = _run(capsys, "--guard-mode", "device",
                    "--guard-rollback-after", "2", "--steps", "9",
                    "--sample-tokens", "0")
    anoms = [r for r in recs if r.get("event") == "anomaly"]
    rolls = [r for r in recs if r.get("event") == "rollback"]
    assert [a["step"] for a in anoms] == [4, 5]
    assert len(rolls) == 1 and rolls[0]["to_step"] == 0
    summary = [r for r in recs if r.get("event") == "guard_summary"]
    assert summary[0]["anomalies"] == 2
    assert summary[0]["rollbacks"] == 1
    steps = [r for r in recs if "loss" in r and "event" not in r]
    assert np.isfinite(steps[-1]["loss"])


def test_device_guard_fused_adam_step():
    """The FusedAdam fused_step honors guard="device" too (the t
    counter must also hold on a skipped step)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from icikit.models.transformer import (FusedAdam, TransformerConfig,
                                           init_params)
    from icikit.models.transformer.model import (make_model_mesh,
                                                 make_train_step)

    cfg = TransformerConfig(vocab=32, d_model=32, n_heads=2, d_head=16,
                            d_ff=64, n_layers=1, max_seq=16,
                            compute_dtype="float32")
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), cfg, mesh)
    opt, step = make_train_step(mesh, cfg, FusedAdam(1e-3),
                                guard="device")
    opt_state = opt.init(params)
    rng = np.random.default_rng(0)
    tok = jnp.asarray(rng.integers(0, 32, (2, 16)), jnp.int32)
    tgt = jnp.asarray(rng.integers(0, 32, (2, 16)), jnp.int32)
    bad = dict(params)
    bad["w1"] = bad["w1"].at[0, 0, 0].set(jnp.inf)
    p2, st2, _, ok = step(bad, opt_state, tok, tgt)
    assert not bool(np.asarray(ok))
    assert int(np.asarray(st2[2])) == 0     # t held
    for k in bad:
        np.testing.assert_array_equal(np.asarray(p2[k]),
                                      np.asarray(bad[k]))
