"""Fleet transport: framing, checksums, RPC, chaos drills.

The wire-integrity contract under test: a flipped payload byte on the
wire is DETECTED mechanically by the frame checksum (never parsed),
a desynced stream fails loudly on the magic, a remote handler error
surfaces as ``RpcError`` without killing the connection, and a client
outlives a server restart through bounded reconnect.
"""

import socket
import threading

import numpy as np
import pytest

from icikit import chaos
from icikit.fleet.transport import (
    ChecksumError,
    RpcClient,
    RpcError,
    RpcServer,
    TransportError,
    recv_msg,
    send_msg,
)


def _pair():
    a, b = socket.socketpair()
    return a, b


def test_roundtrip_msg_and_blobs():
    a, b = _pair()
    blob0 = np.arange(257, dtype=np.int32).tobytes()
    send_msg(a, {"op": "x", "k": [1, 2, 3]}, [blob0, b"\x00" * 7])
    msg, blobs = recv_msg(b)
    assert msg == {"op": "x", "k": [1, 2, 3]}
    assert blobs == [blob0, b"\x00" * 7]
    a.close(); b.close()


def test_empty_blob_list_and_unicode():
    a, b = _pair()
    send_msg(a, {"op": "y", "s": "héllo"})
    msg, blobs = recv_msg(b)
    assert msg["s"] == "héllo" and blobs == []
    a.close(); b.close()


def test_strict_json_rejects_nan():
    a, b = _pair()
    with pytest.raises(ValueError):
        send_msg(a, {"op": "z", "v": float("nan")})
    a.close(); b.close()


def test_desync_bad_magic_detected():
    a, b = _pair()
    a.sendall(b"junkjunkjunkjunkjunk")
    with pytest.raises(TransportError):
        recv_msg(b)
    a.close(); b.close()


def test_wire_flip_detected_by_checksum_recv():
    """The ``fleet.rpc.recv`` SDC drill: rot applied to the received
    payload BEFORE verification must trip the frame checksum."""
    a, b = _pair()
    send_msg(a, {"op": "x", "payload": list(range(64))})
    plan = chaos.FaultPlan(rates={"corrupt:fleet.rpc.recv": 1.0},
                           seed=3)
    with chaos.inject(plan):
        with pytest.raises(ChecksumError):
            recv_msg(b)
    assert plan.fired("corrupt", "fleet.rpc.recv")
    a.close(); b.close()


def test_wire_flip_detected_by_checksum_send():
    """Same detection from the send side: the probe corrupts AFTER
    the digest is computed (wire rot, not content rot)."""
    a, b = _pair()
    plan = chaos.FaultPlan(rates={"corrupt:fleet.rpc.send": 1.0},
                           seed=4)
    with chaos.inject(plan):
        send_msg(a, {"op": "x", "payload": list(range(64))})
    with pytest.raises(ChecksumError):
        recv_msg(b)
    a.close(); b.close()


def test_clean_armed_plan_identical():
    """An armed-but-cold plan must not perturb the bytes (the
    clean-armed-run discipline every chaos site carries)."""
    a, b = _pair()
    plan = chaos.FaultPlan(rates={"corrupt:fleet.rpc.recv": 0.0},
                           seed=5)
    with chaos.inject(plan):
        send_msg(a, {"op": "x", "k": 1}, [b"abc"])
        msg, blobs = recv_msg(b)
    assert msg == {"op": "x", "k": 1} and blobs == [b"abc"]
    a.close(); b.close()


def _echo_handler(op, msg, blobs):
    if op == "boom":
        raise ValueError("kaboom")
    return {"echo": op, **msg}, blobs


def test_rpc_echo_and_error():
    srv = RpcServer(_echo_handler)
    try:
        cli = RpcClient(srv.addr)
        reply, blobs = cli.call("ping", {"n": 3}, [b"blob"])
        assert reply["echo"] == "ping" and reply["n"] == 3
        assert blobs == [b"blob"]
        # a handler error raises RpcError and the connection survives
        with pytest.raises(RpcError) as ei:
            cli.call("boom")
        assert ei.value.etype == "ValueError"
        reply, _ = cli.call("ping", {"n": 4})
        assert reply["n"] == 4
        cli.close()
    finally:
        srv.close()


def test_rpc_client_reconnects_after_server_restart():
    from icikit.utils.net import free_port
    try:
        port = free_port("127.0.0.1")
    except OSError as e:  # pragma: no cover
        pytest.skip(f"cannot bind a local port: {e}")
    srv = RpcServer(_echo_handler, port=port)
    cli = RpcClient(srv.addr, retries=5, first_backoff=0.05)
    assert cli.call("a")[0]["echo"] == "a"
    srv.close()
    # restart on the SAME port (SO_REUSEADDR in utils.net) while the
    # client retries with backoff
    def restart():
        nonlocal srv2
        srv2 = RpcServer(_echo_handler, port=port)
    srv2 = None
    t = threading.Timer(0.1, restart)
    t.start()
    try:
        assert cli.call("b")[0]["echo"] == "b"
    finally:
        t.join()
        cli.close()
        if srv2 is not None:
            srv2.close()


def test_rpc_reconnect_during_partial_frame():
    """The peer dies AFTER the length prefix, BEFORE the payload —
    the nastiest tear: the reader is committed to a frame that will
    never finish. The short read must surface as ``TransportError``
    (not a hang, not a parse of garbage) and the bounded-backoff
    reconnect must carry the SAME call to a real server."""
    import struct

    from icikit.fleet.transport import MAGIC
    from icikit.utils.net import free_port

    try:
        port = free_port("127.0.0.1")
    except OSError as e:  # pragma: no cover
        pytest.skip(f"cannot bind a local port: {e}")
    lsn = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    lsn.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lsn.bind(("127.0.0.1", port))
    lsn.listen(1)
    srv2 = None

    def half_frame_then_die():
        nonlocal srv2
        conn, _ = lsn.accept()
        conn.recv(1 << 16)             # swallow the request
        # a frame header promising 4096 bytes that never arrive
        conn.sendall(MAGIC + struct.pack(">Q", 4096))
        conn.close()
        lsn.close()
        srv2 = RpcServer(_echo_handler, port=port)

    t = threading.Thread(target=half_frame_then_die)
    t.start()
    cli = RpcClient(("127.0.0.1", port), retries=6,
                    first_backoff=0.05, max_backoff=0.5)
    try:
        reply, _ = cli.call("ping", {"n": 9})
        assert reply["echo"] == "ping" and reply["n"] == 9
    finally:
        t.join()
        cli.close()
        if srv2 is not None:
            srv2.close()


def test_rpc_checksum_retry_is_bounded():
    """Permanent wire rot exhausts the bounded retries and raises —
    the transport never spins forever."""
    srv = RpcServer(_echo_handler)
    cli = RpcClient(srv.addr, retries=2, first_backoff=0.01)
    plan = chaos.FaultPlan(rates={"corrupt:fleet.rpc.recv": 1.0},
                           seed=6)
    try:
        with chaos.inject(plan):
            with pytest.raises((ChecksumError, OSError)):
                cli.call("ping", {"n": 1})
    finally:
        cli.close()
        srv.close()
