"""Deterministic fault injection (`icikit.chaos`): same plan, same
faults — and strictly zero overhead when disabled.

The reference can only provoke failures by hand (kill a PBS job,
yank a node); here a drill is an input: a (seed, rates | schedule)
plan whose decisions are pure hashes of (seed, kind, site, call-index),
independent of thread interleaving and global RNG state."""

import threading
import tracemalloc

import numpy as np
import pytest

from icikit import chaos
from icikit.chaos import FaultPlan, InjectedDeath, InjectedIOError


def _drive(plan, sites, calls=40):
    """Probe every (kind, site) `calls` times under the plan; return
    the fired-fault log."""
    with chaos.inject(plan):
        for _ in range(calls):
            for s in sites:
                try:
                    chaos.maybe_die(s)
                except InjectedDeath:
                    pass
                chaos.maybe_delay(s)
                try:
                    chaos.maybe_io_fail(s)
                except InjectedIOError:
                    pass
                chaos.maybe_corrupt(s, np.zeros(4, np.float32))
    return list(plan.log)


def test_same_seed_same_schedule():
    sites = ["w.0", "w.1", "ckpt.save"]
    mk = lambda: FaultPlan(seed=7, delay_s=0.0, rates={
        "die:w.*": 0.3, "io:ckpt.*": 0.5, "corrupt:w.1": 0.2})
    a = _drive(mk(), sites)
    b = _drive(mk(), sites)
    assert a and a == b


def test_different_seed_different_schedule():
    sites = ["w.0", "w.1"]
    a = _drive(FaultPlan(seed=1, delay_s=0.0, rates={"die:w.*": 0.3}),
               sites)
    b = _drive(FaultPlan(seed=2, delay_s=0.0, rates={"die:w.*": 0.3}),
               sites)
    assert a != b


def test_decisions_independent_of_interleaving():
    """The n-th probe of a (kind, site) fires identically no matter how
    calls from different sites interleave — the property that makes a
    multi-threaded drill replayable."""
    sites = [f"w.{i}" for i in range(4)]
    plan_seq = FaultPlan(seed=3, rates={"die:w.*": 0.4})
    with chaos.inject(plan_seq):
        for s in sites:          # site-major order
            for _ in range(50):
                try:
                    chaos.maybe_die(s)
                except InjectedDeath:
                    pass

    plan_thr = FaultPlan(seed=3, rates={"die:w.*": 0.4})

    def hammer(s):
        for _ in range(50):
            try:
                chaos.maybe_die(s)
            except InjectedDeath:
                pass

    with chaos.inject(plan_thr):
        ts = [threading.Thread(target=hammer, args=(s,)) for s in sites]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    assert sorted(plan_seq.log) == sorted(plan_thr.log)


def test_rate_one_always_fires_rate_zero_never():
    plan = FaultPlan(seed=0, rates={"die:a": 1.0, "die:b": 0.0})
    with chaos.inject(plan):
        for _ in range(10):
            with pytest.raises(InjectedDeath):
                chaos.maybe_die("a")
            chaos.maybe_die("b")  # never raises
    assert plan.fired("die", "a") == 10
    assert plan.fired("die", "b") == 0


def test_schedule_fires_exact_call_indices():
    plan = FaultPlan(schedule={"die:w.1": (0, 2)})
    hits = []
    with chaos.inject(plan):
        for n in range(5):
            try:
                chaos.maybe_die("w.1")
            except InjectedDeath:
                hits.append(n)
            chaos.maybe_die("w.0")  # glob does not match: never fires
    assert hits == [0, 2]


def test_glob_site_matching():
    plan = FaultPlan(rates={"io:ckpt.*": 1.0})
    with chaos.inject(plan):
        with pytest.raises(InjectedIOError):
            chaos.maybe_io_fail("ckpt.save")
        chaos.maybe_io_fail("train.step")  # no match


def test_corrupt_is_deterministic_single_bitflip():
    a = np.arange(32, dtype=np.float32)
    mk = lambda: FaultPlan(seed=11, rates={"corrupt:x": 1.0})
    with chaos.inject(mk()):
        c1 = chaos.maybe_corrupt("x", a)
    with chaos.inject(mk()):
        c2 = chaos.maybe_corrupt("x", a)
    np.testing.assert_array_equal(c1, c2)      # replayable
    assert not np.array_equal(c1, a)           # and it did corrupt
    xor = np.frombuffer(c1.tobytes(), np.uint8) ^ np.frombuffer(
        a.tobytes(), np.uint8)
    assert int(np.unpackbits(xor).sum()) == 1  # exactly one bit
    np.testing.assert_array_equal(a, np.arange(32, dtype=np.float32))


def test_corrupt_nan_mode_poisons_one_element():
    a = np.ones(16, np.float32)
    plan = FaultPlan(rates={"corrupt:x": 1.0}, corrupt_mode="nan")
    with chaos.inject(plan):
        c = chaos.maybe_corrupt("x", a)
    assert int(np.isnan(c).sum()) == 1
    assert not np.isnan(a).any()


def test_disabled_probes_are_inert_and_allocation_free():
    """No plan armed: every probe is a global read + None check. The
    hot path must not allocate — `solve_dynamic` probes on every pull
    and the train loop on every step, drill or no drill."""
    assert chaos.active() is None
    arr = np.zeros(8, np.float32)
    site = "hot.path"
    probes = [chaos.maybe_die, chaos.maybe_delay, chaos.maybe_io_fail]
    for p in probes:   # warm up: frames, method caches
        p(site)
    assert chaos.maybe_corrupt(site, arr) is arr  # same object, no copy
    loops = list(range(2000))
    tracemalloc.start()
    before = tracemalloc.take_snapshot()
    for _ in loops:
        chaos.maybe_die(site)
        chaos.maybe_delay(site)
        chaos.maybe_io_fail(site)
        chaos.maybe_corrupt(site, arr)
    after = tracemalloc.take_snapshot()
    tracemalloc.stop()
    # attribute to chaos.py only: the process has background threads
    # (XLA, executors) that allocate on their own schedule
    flt = [tracemalloc.Filter(True, chaos.__file__)]
    stats = after.filter_traces(flt).compare_to(
        before.filter_traces(flt), "lineno")
    # a handful of one-off interpreter allocations (frame objects on a
    # cold free-list) are tolerated; anything scaling with the 8000
    # probe calls is not
    new_blocks = sum(s.count_diff for s in stats if s.count_diff > 0)
    new_bytes = sum(s.size_diff for s in stats if s.size_diff > 0)
    assert new_blocks < 50 and new_bytes < 4096, (
        f"disabled probes allocate per call: {new_blocks} blocks, "
        f"{new_bytes} bytes over 8000 calls")


def test_inject_restores_previous_plan():
    outer, inner = FaultPlan(seed=1), FaultPlan(seed=2)
    assert chaos.active() is None
    with chaos.inject(outer):
        assert chaos.active() is outer
        with chaos.inject(inner):
            assert chaos.active() is inner
        assert chaos.active() is outer
    assert chaos.active() is None


def test_injected_io_error_is_oserror():
    # production retry paths catch OSError; the drill must ride them
    assert issubclass(InjectedIOError, OSError)


def test_io_retry_retries_transient_failures():
    plan = FaultPlan(schedule={"io:x": (0,)})  # first attempt only
    with chaos.inject(plan):
        out = chaos.io_retry("x", lambda: "ok", first_backoff=0.001)
    assert out == "ok"
    assert plan.fired("io") == 1  # one failure, one successful retry


def test_io_retry_bounded_then_raises():
    plan = FaultPlan(rates={"io:x": 1.0})  # storage is down, not flaky
    with chaos.inject(plan):
        with pytest.raises(InjectedIOError):
            chaos.io_retry("x", lambda: "ok", retries=2,
                           first_backoff=0.001)
    assert plan.fired("io") == 3  # initial attempt + 2 retries, no more


def test_io_retry_non_oserror_propagates_immediately():
    calls = []

    def broken():
        calls.append(1)
        raise ValueError("logic bug, not weather")

    with pytest.raises(ValueError):
        chaos.io_retry("x", broken, first_backoff=0.001)
    assert len(calls) == 1  # never retried


def test_env_spec_parsing():
    plan = chaos.plan_from_spec(
        "seed=7; die:w.*=0.25; io:ckpt.*=@1+3; delay_s=0.5;"
        " corrupt_mode=nan")
    assert plan.seed == 7
    assert plan.rates == {"die:w.*": 0.25}
    assert plan.schedule == {"io:ckpt.*": (1, 3)}
    assert plan.delay_s == 0.5
    assert plan.corrupt_mode == "nan"


@pytest.mark.parametrize("spec", [
    "frob=1",                 # unknown field
    "explode:w.*=0.5",        # unknown fault kind
    "die:w.*",                # missing =value
])
def test_env_spec_rejects_garbage(spec):
    with pytest.raises(ValueError):
        chaos.plan_from_spec(spec)


def test_unknown_kind_rejected_at_plan_construction():
    with pytest.raises(ValueError):
        FaultPlan(rates={"explode:w.*": 0.5})


def test_env_var_arms_plan_at_import():
    """ICIKIT_CHAOS in the environment arms a plan before any probe
    runs — the no-code-changes path for drilling a deployed entry
    point. Checked in a subprocess: arming happens at import time."""
    import os
    import subprocess
    import sys

    code = ("import icikit.chaos as c; p = c.active(); "
            "print(p.seed, sorted(p.rates), sorted(p.schedule))")
    env = dict(os.environ,
               ICIKIT_CHAOS="seed=5;die:w.*=0.5;io:ckpt.*=@2+7")
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-2000:]
    assert out.stdout.split() == ["5", "['die:w.*']", "['io:ckpt.*']"]
