"""Zigzag ring attention vs the dense oracle — exactness, layout
round-trip, gradients, any-p support, and the model integration."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from icikit.models.attention import dense_attention, zigzag_attention
from icikit.utils.mesh import make_mesh, shard_along


def _qkv(b=2, s=32, h=4, d=8, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(
        rng.standard_normal((b, s, h, d)).astype(dtype))
    return mk(), mk(), mk()


def _shard(mesh, *arrs):
    return tuple(shard_along(a, mesh, dim=1) for a in arrs)


@pytest.mark.parametrize("causal", [False, True])
def test_zigzag_matches_dense(mesh8, causal):
    q, k, v = _qkv()
    expected = np.asarray(dense_attention(q, k, v, causal=causal))
    qs, ks, vs = _shard(mesh8, q, k, v)
    out = np.asarray(zigzag_attention(qs, ks, vs, mesh8, causal=causal))
    np.testing.assert_allclose(out, expected, rtol=2e-5, atol=2e-5)


def test_zigzag_layout_roundtrip(mesh8):
    """_to_zigzag/_from_zigzag are inverse, and the forward layout puts
    chunks (r, 2p-1-r) on device r — checked directly on the helpers
    (the public path is covered by test_zigzag_matches_dense)."""
    from functools import partial

    from jax.sharding import PartitionSpec as P

    from icikit.models.attention.zigzag import _from_zigzag, _to_zigzag
    from icikit.parallel.shmap import shard_map

    p = 8
    x = jnp.arange(2 * 32 * 1 * 1, dtype=jnp.int32).reshape(2, 32, 1, 1)
    xs = shard_along(x, mesh8, dim=1)

    def rt(blk):
        return _from_zigzag(_to_zigzag(blk, "p", p), "p", p)

    out = shard_map(rt, mesh=mesh8, in_specs=P(None, "p"),
                    out_specs=P(None, "p"))(xs)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))

    def fwd(blk):
        return _to_zigzag(blk, "p", p)

    zz = np.asarray(shard_map(fwd, mesh=mesh8, in_specs=P(None, "p"),
                              out_specs=P(None, "p"))(xs))
    # device r holds chunks (r, 2p-1-r): verify against the closed form
    chunks = np.asarray(x).reshape(2, 2 * p, 32 // (2 * p), 1, 1)
    for r in range(p):
        got = zz[:, r * 4:(r + 1) * 4]
        exp = np.concatenate([chunks[:, r], chunks[:, 2 * p - 1 - r]],
                             axis=1)
        np.testing.assert_array_equal(got, exp)


def test_zigzag_non_pow2_mesh():
    mesh = make_mesh(6)
    q, k, v = _qkv(s=36, seed=2)
    expected = np.asarray(dense_attention(q, k, v, causal=True))
    qs, ks, vs = _shard(mesh, q, k, v)
    out = np.asarray(zigzag_attention(qs, ks, vs, mesh, causal=True))
    np.testing.assert_allclose(out, expected, rtol=2e-5, atol=2e-5)


def test_zigzag_gradients_match_dense(mesh8):
    q, k, v = _qkv(s=16, seed=3)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    def loss_zz(q, k, v):
        return jnp.sum(zigzag_attention(q, k, v, mesh8, causal=True) ** 2)

    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    qs, ks, vs = _shard(mesh8, q, k, v)
    g_zz = jax.grad(loss_zz, argnums=(0, 1, 2))(qs, ks, vs)
    for gd, gz in zip(g_dense, g_zz):
        np.testing.assert_allclose(np.asarray(gz), np.asarray(gd),
                                   rtol=5e-4, atol=5e-5)


def test_zigzag_p1_degenerate(mesh1):
    q, k, v = _qkv(seed=5)
    expected = np.asarray(dense_attention(q, k, v, causal=True))
    out = np.asarray(zigzag_attention(q, k, v, mesh1, causal=True))
    np.testing.assert_allclose(out, expected, rtol=2e-5, atol=2e-5)


def test_zigzag_shape_validation(mesh8):
    q, k, v = _qkv(s=24)  # 24 divides by p=8 but not 2p=16
    with pytest.raises(ValueError, match="zigzag"):
        zigzag_attention(q, k, v, mesh8, causal=True)
    # non-causal delegates to the ring: p-divisibility suffices
    out = zigzag_attention(q, k, v, mesh8, causal=False)
    assert out.shape == q.shape
    q, k, v = _qkv(s=20)  # 20 does not divide by p=8 either
    with pytest.raises(ValueError, match="sequence length"):
        zigzag_attention(q, k, v, mesh8, causal=False)


def test_model_zigzag_schedule_matches_ring():
    """The flagship's sequence_schedule='zigzag' reproduces the ring
    schedule's loss exactly (same math, different layout)."""
    import dataclasses

    from jax.sharding import NamedSharding, PartitionSpec as P

    from icikit.models.transformer import (
        TransformerConfig, init_params, loss_fn)
    from icikit.models.transformer.model import make_model_mesh

    cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4, d_head=8,
                            d_ff=64, n_layers=2, max_seq=16,
                            compute_dtype="float32")
    mesh = make_model_mesh(dp=1, tp=1, sp=4)
    params = init_params(jax.random.key(0), cfg, mesh)
    sh = NamedSharding(mesh, P("dp", "sp"))
    tok = jax.device_put(
        jnp.arange(2 * 16, dtype=jnp.int32).reshape(2, 16) % 64, sh)
    tgt = jax.device_put(jnp.ones((2, 16), jnp.int32), sh)
    loss_ring, _ = loss_fn(params, tok, tgt, mesh, cfg)
    zz_cfg = dataclasses.replace(cfg, sequence_schedule="zigzag")
    loss_zz, _ = loss_fn(params, tok, tgt, mesh, zz_cfg)
    np.testing.assert_allclose(float(loss_zz), float(loss_ring),
                               rtol=1e-5)


def test_zigzag_gqa_head_divisibility_validated(mesh8):
    q, k, v = _qkv(s=32, h=4)
    with pytest.raises(ValueError, match="multiple of K/V heads"):
        zigzag_attention(q, k[:, :, :3], v[:, :, :3], mesh8, causal=True)
