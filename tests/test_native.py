"""Tests for the native C++ runtime (icikit/native): guard, timer,
dataset parser, DFS solver, thread-pool batch driver.

The native solver must be bit-identical to the Python oracle and the
JAX kernel — same (i, j, dir) move order, same first solution, same
node counts — so every backend of the DLB study is interchangeable.
"""

import subprocess
import sys
import textwrap

import numpy as np
import pytest

from icikit import native
from icikit.models.solitaire.dataset import generate_dataset, save_dataset
from icikit.models.solitaire.game import solve_one_py
from icikit.models.solitaire.scheduler import solve_host


pytestmark = pytest.mark.skipif(
    not native.available(),
    reason=f"native runtime unavailable: {native.build_error()}")


def test_native_available_on_this_image():
    # The build toolchain is baked into this image; the native path must
    # be active, not silently degraded.
    assert native.available(), native.build_error()


def test_monotonic_clock():
    a = native.monotonic_s()
    b = native.monotonic_s()
    assert b >= a > 0


def test_parse_boards_matches_python():
    ds = generate_dataset(40, "easy", seed=21)
    text = (f"{len(ds)}\n" + "\n".join(ds.to_strings()) + "\n").encode()
    pegs, playable = native.parse_boards(text)
    assert (pegs == ds.pegs).all()
    assert (playable == ds.playable).all()


def test_parse_boards_errors():
    with pytest.raises(ValueError, match="header"):
        native.parse_boards(b"x\n")
    with pytest.raises(ValueError, match="fewer rows"):
        native.parse_boards(b"3\n" + b"1" * 25 + b"\n")
    with pytest.raises(ValueError, match="row"):
        native.parse_boards(b"1\n111\n")


def test_parse_boards_tolerates_extra_whitespace():
    row = b"1" * 25
    pegs, _ = native.parse_boards(b"  2 \r\n" + row + b"\r\n\n " + row)
    assert len(pegs) == 2


def test_native_solver_matches_oracle():
    ds = generate_dataset(48, "medium", seed=31)
    for i in range(len(ds)):
        ok, ms, nodes = solve_one_py(int(ds.pegs[i]), int(ds.playable[i]))
        nok, nms, nnodes = native.solve(int(ds.pegs[i]), int(ds.playable[i]))
        assert ok == nok
        assert nodes == nnodes
        if ok:
            assert ms == nms


def test_native_step_limit():
    ds = generate_dataset(4, "medium", seed=33, solvable_fraction=0.0)
    for i in range(len(ds)):
        ok, ms, nodes = native.solve(int(ds.pegs[i]), int(ds.playable[i]),
                                     max_steps=3)
        assert nodes <= 3


def test_native_batch_threaded_deterministic():
    ds = generate_dataset(100, "easy", seed=41)
    s1, nm1, mv1, st1 = native.solve_batch(ds.pegs, ds.playable, n_threads=1)
    s8, nm8, mv8, st8 = native.solve_batch(ds.pegs, ds.playable, n_threads=8)
    assert (s1 == s8).all()
    assert (nm1 == nm8).all()
    assert (mv1 == mv8).all()
    assert (st1 == st8).all()


def test_solve_host_report():
    ds = generate_dataset(64, "easy", seed=51)
    rep = solve_host(ds, n_threads=4)
    oracle = sum(solve_one_py(int(ds.pegs[i]), int(ds.playable[i]))[0]
                 for i in range(len(ds)))
    assert rep.n_solutions == oracle
    assert rep.strategy == "host"


def test_empty_batch():
    s, nm, mv, st = native.solve_batch(
        np.zeros(0, np.uint32), np.zeros(0, np.uint32))
    assert len(s) == 0


def test_watchdog_soft_counts_alarm():
    # Soft mode: the trapped SIGALRM increments a counter instead of
    # killing the process — exercised in a subprocess anyway for
    # isolation from the test runner's signal state.
    code = textwrap.dedent("""
        import time
        from icikit import native
        assert native.available()
        native.watchdog_soft(True)
        assert native.install_traps()
        before = native.trap_count()
        native.watchdog(1)
        time.sleep(1.5)
        assert native.trap_count() == before + 1
        print("SOFT-OK")
    """)
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=120, cwd="/root/repo")
    assert "SOFT-OK" in r.stdout, r.stderr


def test_watchdog_hard_kills_runaway():
    # Hard mode is the reference's whole point (utilities.cc:49-58): a
    # hung run dies with a diagnostic instead of wedging the queue.
    code = textwrap.dedent("""
        import time
        from icikit.utils.guard import chopsigs
        chopsigs(1)
        time.sleep(30)
        print("SHOULD-NOT-PRINT")
    """)
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=120, cwd="/root/repo")
    assert "SHOULD-NOT-PRINT" not in r.stdout
    assert "watchdog" in r.stderr or "ERROR" in r.stderr


def test_disarm_restores_default_dispositions():
    # After a guarded run disarms, the process must stop treating
    # signals as icikit-fatal: a raised SIGALRM should produce the
    # *default* death (killed by signal 14), not the trap handler's
    # _exit(2) + diagnostic. Leaving the handler installed turned
    # teardown-time signals into truncated-output suite deaths.
    code = textwrap.dedent("""
        import os, signal
        from icikit.utils.guard import chopsigs, disarm
        chopsigs(600)
        disarm()
        os.kill(os.getpid(), signal.SIGALRM)
        print("SHOULD-NOT-PRINT")
    """)
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=120, cwd="/root/repo")
    assert r.returncode == -14, r  # default SIGALRM death
    assert "icikit terminated" not in r.stderr, r.stderr


def test_load_dataset_uses_native_path(tmp_path):
    ds = generate_dataset(16, "easy", seed=61)
    path = tmp_path / "g.dat"
    save_dataset(path, ds)
    from icikit.models.solitaire.dataset import load_dataset
    back = load_dataset(path)
    assert (back.pegs == ds.pegs).all()
