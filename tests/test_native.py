"""Tests for the native C++ runtime (icikit/native): guard, timer,
dataset parser, DFS solver, thread-pool batch driver.

The native solver must be bit-identical to the Python oracle and the
JAX kernel — same (i, j, dir) move order, same first solution, same
node counts — so every backend of the DLB study is interchangeable.
"""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from icikit import native
from icikit.models.solitaire.dataset import generate_dataset, save_dataset
from icikit.models.solitaire.game import solve_one_py
from icikit.models.solitaire.scheduler import solve_host


pytestmark = pytest.mark.skipif(
    not native.available(),
    reason=f"native runtime unavailable: {native.build_error()}")


def test_native_available_on_this_image():
    # The build toolchain is baked into this image; the native path must
    # be active, not silently degraded.
    assert native.available(), native.build_error()


def test_monotonic_clock():
    a = native.monotonic_s()
    b = native.monotonic_s()
    assert b >= a > 0


def test_parse_boards_matches_python():
    ds = generate_dataset(40, "easy", seed=21)
    text = (f"{len(ds)}\n" + "\n".join(ds.to_strings()) + "\n").encode()
    pegs, playable = native.parse_boards(text)
    assert (pegs == ds.pegs).all()
    assert (playable == ds.playable).all()


def test_parse_boards_errors():
    with pytest.raises(ValueError, match="header"):
        native.parse_boards(b"x\n")
    with pytest.raises(ValueError, match="fewer rows"):
        native.parse_boards(b"3\n" + b"1" * 25 + b"\n")
    with pytest.raises(ValueError, match="row"):
        native.parse_boards(b"1\n111\n")


def test_parse_boards_tolerates_extra_whitespace():
    row = b"1" * 25
    pegs, _ = native.parse_boards(b"  2 \r\n" + row + b"\r\n\n " + row)
    assert len(pegs) == 2


def test_native_solver_matches_oracle():
    ds = generate_dataset(48, "medium", seed=31)
    for i in range(len(ds)):
        ok, ms, nodes = solve_one_py(int(ds.pegs[i]), int(ds.playable[i]))
        nok, nms, nnodes = native.solve(int(ds.pegs[i]), int(ds.playable[i]))
        assert ok == nok
        assert nodes == nnodes
        if ok:
            assert ms == nms


def test_native_step_limit():
    ds = generate_dataset(4, "medium", seed=33, solvable_fraction=0.0)
    for i in range(len(ds)):
        ok, ms, nodes = native.solve(int(ds.pegs[i]), int(ds.playable[i]),
                                     max_steps=3)
        assert nodes <= 3


def test_native_batch_threaded_deterministic():
    ds = generate_dataset(100, "easy", seed=41)
    s1, nm1, mv1, st1 = native.solve_batch(ds.pegs, ds.playable, n_threads=1)
    s8, nm8, mv8, st8 = native.solve_batch(ds.pegs, ds.playable, n_threads=8)
    assert (s1 == s8).all()
    assert (nm1 == nm8).all()
    assert (mv1 == mv8).all()
    assert (st1 == st8).all()


def test_solve_host_report():
    ds = generate_dataset(64, "easy", seed=51)
    rep = solve_host(ds, n_threads=4)
    oracle = sum(solve_one_py(int(ds.pegs[i]), int(ds.playable[i]))[0]
                 for i in range(len(ds)))
    assert rep.n_solutions == oracle
    assert rep.strategy == "host"


def test_empty_batch():
    s, nm, mv, st = native.solve_batch(
        np.zeros(0, np.uint32), np.zeros(0, np.uint32))
    assert len(s) == 0


def test_solve_batch_resolves_default_thread_count():
    """n_threads <= 0 resolves in Python (mirroring solver.cc's
    hardware_concurrency rule), so the worker-id domain returned with
    return_workers is always known to the caller — previously the C++
    side resolved it privately and the ids' range was unknowable."""
    ds = generate_dataset(32, "easy", seed=61)
    resolved = native.resolve_n_threads(0)
    assert resolved == (os.cpu_count() or 1)
    assert native.resolve_n_threads(3) == 3
    out = native.solve_batch(ds.pegs, ds.playable, n_threads=0,
                             return_workers=True)
    workers = out[4]
    assert workers.min() >= 0 and workers.max() < resolved


def test_build_lock_serializes_make(tmp_path):
    """The lazy build runs under an flock on a sentinel next to the
    library (two processes first-loading concurrently serialize on the
    link; neither can dlopen a partially-written .so). The sentinel
    must exist after a load on this image."""
    import icikit.native as nat

    assert native.available()
    assert os.path.exists(os.path.join(
        os.path.dirname(os.path.abspath(nat.__file__)), ".build.lock"))


def test_cdll_retried_once_after_failed_probe(monkeypatch):
    """A CDLL that fails on the first probe (torn read mid-replace by
    a concurrent builder) is retried once after a locked re-make; a
    failed dlopen maps nothing, so the retry is sound."""
    import ctypes

    import icikit.native as nat

    real_cdll = ctypes.CDLL
    calls = {"n": 0}

    def flaky_cdll(path, *a, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("simulated torn .so")
        return real_cdll(path, *a, **kw)

    monkeypatch.setattr(nat.ctypes, "CDLL", flaky_cdll)
    old_lib, old_err = nat._lib, nat._build_error
    nat._lib = nat._build_error = None
    try:
        assert nat.available(), nat.build_error()
        assert calls["n"] == 2  # failed once, retried once, loaded
    finally:
        nat._lib, nat._build_error = old_lib, old_err


def test_watchdog_soft_counts_alarm():
    # Soft mode: the trapped SIGALRM increments a counter instead of
    # killing the process — exercised in a subprocess anyway for
    # isolation from the test runner's signal state.
    code = textwrap.dedent("""
        import time
        from icikit import native
        assert native.available()
        native.watchdog_soft(True)
        assert native.install_traps()
        before = native.trap_count()
        native.watchdog(1)
        time.sleep(1.5)
        assert native.trap_count() == before + 1
        print("SOFT-OK")
    """)
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=120, cwd="/root/repo")
    assert "SOFT-OK" in r.stdout, r.stderr


def test_watchdog_hard_kills_runaway():
    # Hard mode is the reference's whole point (utilities.cc:49-58): a
    # hung run dies with a diagnostic instead of wedging the queue.
    code = textwrap.dedent("""
        import time
        from icikit.utils.guard import chopsigs
        chopsigs(1)
        time.sleep(30)
        print("SHOULD-NOT-PRINT")
    """)
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=120, cwd="/root/repo")
    assert "SHOULD-NOT-PRINT" not in r.stdout
    assert "watchdog" in r.stderr or "ERROR" in r.stderr


def test_disarm_restores_default_dispositions():
    # After a guarded run disarms, the process must stop treating
    # signals as icikit-fatal: a raised SIGALRM should produce the
    # *default* death (killed by signal 14), not the trap handler's
    # _exit(2) + diagnostic. Leaving the handler installed turned
    # teardown-time signals into truncated-output suite deaths.
    code = textwrap.dedent("""
        import os, signal
        from icikit.utils.guard import chopsigs, disarm
        chopsigs(600)
        disarm()
        os.kill(os.getpid(), signal.SIGALRM)
        print("SHOULD-NOT-PRINT")
    """)
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=120, cwd="/root/repo")
    assert r.returncode == -14, r  # default SIGALRM death
    assert "icikit terminated" not in r.stderr, r.stderr


def test_load_dataset_uses_native_path(tmp_path):
    ds = generate_dataset(16, "easy", seed=61)
    path = tmp_path / "g.dat"
    save_dataset(path, ds)
    from icikit.models.solitaire.dataset import load_dataset
    back = load_dataset(path)
    assert (back.pegs == ds.pegs).all()
