"""Sampled decode under the schedule-invariant counter key discipline
(round 12), and rejection-sampled speculation.

The load-bearing invariants:

- a row's sampled continuation is a pure function of (prompt, seed,
  knobs) — bitwise invariant to batch composition and mesh layout
  (the key is ``fold_in(fold_in(base, seed), position)``, never the
  batch slot, dp shard, or step count);
- ``speculative_sample_generate`` is bitwise identical to
  ``sample_generate`` for any verify width / drafter (the rejection
  construction draws each position's token from the target
  distribution under the SAME position key the sequential loop would
  use — with deterministic one-hot proposals, accepting iff the draw
  equals the draft IS ``min(1, p/q)`` acceptance with residual
  resampling);
- the ``temperature → 0`` limit is the greedy longest-prefix accept
  path, bitwise;
- and, beyond bitwise pins, a two-sample chi-square check that
  spec-sampled token frequencies match baseline frequencies at
  matched (temperature, top_p) across DISJOINT seed sets — the
  distribution-exactness claim tested statistically, not just by key
  bookkeeping.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from icikit.models.transformer import TransformerConfig, init_params
from icikit.models.transformer.decode import (
    greedy_generate,
    sample_generate,
)
from icikit.models.transformer.model import make_model_mesh
from icikit.models.transformer.speculative import (
    speculative_generate,
    speculative_sample_generate,
)

CFG = TransformerConfig(vocab=61, d_model=32, n_heads=4, d_head=8,
                        d_ff=64, n_layers=2, max_seq=64,
                        compute_dtype="float32")


def _put(mesh, arr):
    return jax.device_put(jnp.asarray(arr),
                          NamedSharding(mesh, P("dp", None)))


def _prompts(b, s, seed=0, vocab=61):
    rng = np.random.default_rng(seed)
    return rng.integers(0, vocab, (b, s)).astype(np.int32)


def test_sample_invariant_to_batch_composition():
    """Row r of a batch == the same (prompt, seed) sampled alone: the
    draw depends on the request's stream and position only, never on
    what else rides the batch — the prerequisite for the engine ≡
    generate sampled identity pin."""
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    prompts = _prompts(3, 7, seed=1)
    key = jax.random.key(5)
    batch = np.asarray(sample_generate(
        params, _put(mesh, prompts), mesh, CFG, 9, key,
        temperature=1.1, top_p=0.9, seeds=[3, 9, 5]))
    solo = np.asarray(sample_generate(
        params, _put(mesh, prompts[1:2]), mesh, CFG, 9, key,
        temperature=1.1, top_p=0.9, seeds=[9]))
    np.testing.assert_array_equal(batch[1], solo[0])
    # and a different co-batch leaves the row untouched
    other = np.asarray(sample_generate(
        params, _put(mesh, prompts[1:]), mesh, CFG, 9, key,
        temperature=1.1, top_p=0.9, seeds=[9, 5]))
    np.testing.assert_array_equal(batch[1], other[0])


@pytest.mark.parametrize("dp,tp", [(2, 1), (2, 2)])
def test_sample_invariant_across_meshes(dp, tp):
    """The same batch sampled on dp/tp meshes is bitwise the dp=1
    output — pre-r12 the key folded the dp shard index, which made
    sampled tokens depend on physical placement."""
    mesh1 = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh1)
    prompts = _prompts(4, 6, seed=2)
    key = jax.random.key(1)
    want = np.asarray(sample_generate(
        params, _put(mesh1, prompts), mesh1, CFG, 8, key,
        temperature=1.4, top_p=0.92))
    mesh = make_model_mesh(dp=dp, tp=tp, sp=1)
    params2 = init_params(jax.random.key(0), CFG, mesh)
    got = np.asarray(sample_generate(
        params2, _put(mesh, prompts), mesh, CFG, 8, key,
        temperature=1.4, top_p=0.92))
    np.testing.assert_array_equal(got, want)


def test_sample_temperature_zero_is_greedy_bitwise():
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    prompts = _put(mesh, _prompts(2, 8, seed=3))
    base = np.asarray(greedy_generate(params, prompts, mesh, CFG, 10))
    got = np.asarray(sample_generate(params, prompts, mesh, CFG, 10,
                                     jax.random.key(9),
                                     temperature=0.0))
    np.testing.assert_array_equal(got, base)


@pytest.mark.parametrize("drafter", ["ngram", "shared"])
@pytest.mark.parametrize("k", [2, 4])
def test_spec_sampled_bitwise_vs_sample_generate(drafter, k):
    """The rejection-sampled verify window commits the identical
    sequence the sequential sampled loop draws — for any window width
    and drafter, because proposals only gate how many weights passes
    it takes, never which keyed draw commits."""
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    prompts = _put(mesh, _prompts(3, 8, seed=4))
    key = jax.random.key(2)
    base = np.asarray(sample_generate(
        params, prompts, mesh, CFG, 12, key, temperature=0.9,
        top_p=0.95, seeds=[1, 2, 3]))
    got = np.asarray(speculative_sample_generate(
        params, prompts, mesh, CFG, 12, key, k=k, temperature=0.9,
        top_p=0.95, seeds=[1, 2, 3], drafter=drafter))
    np.testing.assert_array_equal(got, base)


@pytest.mark.parametrize("dp,tp", [(2, 1), (2, 2)])
def test_spec_sampled_identity_sharded(dp, tp):
    mesh = make_model_mesh(dp=dp, tp=tp, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    prompts = _put(mesh, _prompts(4, 6, seed=5))
    key = jax.random.key(3)
    base = np.asarray(sample_generate(
        params, prompts, mesh, CFG, 10, key, temperature=1.2,
        top_k=16))
    got = np.asarray(speculative_sample_generate(
        params, prompts, mesh, CFG, 10, key, k=3, temperature=1.2,
        top_k=16, drafter="ngram"))
    np.testing.assert_array_equal(got, base)


def test_spec_sampled_trained_drafter_identity():
    """The trained early-exit head drafts deterministically too — an
    untrained head proposes near-noise, and identity must hold
    regardless (proposal quality prices throughput, never tokens)."""
    cfg = TransformerConfig(vocab=61, d_model=32, n_heads=4, d_head=8,
                            d_ff=64, n_layers=4, max_seq=64,
                            compute_dtype="float32", draft_head=True)
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), cfg, mesh)
    prompts = _put(mesh, _prompts(2, 6, seed=6))
    key = jax.random.key(4)
    base = np.asarray(sample_generate(
        params, prompts, mesh, cfg, 10, key, temperature=0.8,
        top_p=0.9))
    got = np.asarray(speculative_sample_generate(
        params, prompts, mesh, cfg, 10, key, k=3, temperature=0.8,
        top_p=0.9, drafter="trained"))
    np.testing.assert_array_equal(got, base)


def test_spec_sampled_temperature_zero_is_greedy_accept_bitwise():
    """temperature → 0 pins the whole sampled route onto the existing
    greedy longest-prefix accept path: spec-sampled == greedy spec ==
    greedy generate, bitwise."""
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    prompts = _put(mesh, _prompts(3, 8, seed=7))
    greedy = np.asarray(greedy_generate(params, prompts, mesh, CFG, 10))
    spec_greedy = np.asarray(speculative_generate(
        params, prompts, mesh, CFG, 10, k=3, drafter="ngram"))
    spec_t0 = np.asarray(speculative_sample_generate(
        params, prompts, mesh, CFG, 10, jax.random.key(6), k=3,
        temperature=0.0, drafter="ngram"))
    np.testing.assert_array_equal(spec_greedy, greedy)
    np.testing.assert_array_equal(spec_t0, greedy)


# 99.9% chi-square quantiles, df = 1..15 (two-sample test below)
_CHI2_999 = [10.828, 13.816, 16.266, 18.467, 20.515, 22.458, 24.322,
             26.124, 27.877, 29.588, 31.264, 32.909, 34.528, 36.123,
             37.697]


def _two_sample_chi2(a, b):
    """Two-sample chi-square over pooled bins (combined count >= 10);
    returns (statistic, df)."""
    keep = (a + b) >= 10
    a2 = np.concatenate([a[keep], [a[~keep].sum()]])
    b2 = np.concatenate([b[keep], [b[~keep].sum()]])
    nz = (a2 + b2) > 0
    a2, b2 = a2[nz], b2[nz]
    k1 = np.sqrt(b2.sum() / a2.sum())
    k2 = np.sqrt(a2.sum() / b2.sum())
    stat = float((((k1 * a2 - k2 * b2) ** 2) / (a2 + b2)).sum())
    return stat, len(a2) - 1


@pytest.mark.parametrize("drafter,dp,tp", [("ngram", 1, 1),
                                           ("shared", 2, 2)])
def test_rejection_sampling_chi_square_exactness(drafter, dp, tp):
    """Spec-sampled token frequencies vs baseline sample_generate
    frequencies at matched (temperature, top_p), over DISJOINT seed
    sets — a genuine two-sample test of distribution equality (the
    bitwise pins above use matched seeds; this one would still catch
    a construction that broke exactness while preserving per-seed
    reproducibility)."""
    cfg = TransformerConfig(vocab=11, d_model=16, n_heads=2, d_head=8,
                            d_ff=32, n_layers=1, max_seq=64,
                            compute_dtype="float32")
    mesh = make_model_mesh(dp=dp, tp=tp, sp=1)
    params = init_params(jax.random.key(0), cfg, mesh)
    b, s, n = 16, 6, 12
    prompts = _put(mesh, _prompts(b, s, seed=8, vocab=11))
    key = jax.random.key(7)
    base_toks, spec_toks = [], []
    for rep in range(2):
        seeds_a = np.arange(b) + 1000 * rep
        seeds_b = np.arange(b) + 1000 * rep + 500
        base = np.asarray(sample_generate(
            params, prompts, mesh, cfg, n, key, temperature=1.3,
            top_p=0.9, seeds=seeds_a))
        spec = np.asarray(speculative_sample_generate(
            params, prompts, mesh, cfg, n, key, k=3, temperature=1.3,
            top_p=0.9, seeds=seeds_b, drafter=drafter))
        base_toks.append(base[:, s:].ravel())
        spec_toks.append(spec[:, s:].ravel())
    a = np.bincount(np.concatenate(base_toks), minlength=11)
    bfreq = np.bincount(np.concatenate(spec_toks), minlength=11)
    stat, df = _two_sample_chi2(a.astype(np.float64),
                                bfreq.astype(np.float64))
    assert df >= 1
    crit = _CHI2_999[df - 1]
    assert stat < crit, (
        f"spec-sampled token frequencies diverge from baseline at "
        f"p<0.001: chi2={stat:.2f} > {crit} (df={df})")


def test_sample_seeds_differentiate_identical_prompts():
    """Two rows with the same prompt but different seeds draw
    different continuations; the same seed reproduces bitwise."""
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    p = np.broadcast_to(np.arange(6, dtype=np.int32), (2, 6)).copy()
    key = jax.random.key(0)
    out = np.asarray(sample_generate(
        params, _put(mesh, p), mesh, CFG, 10, key, temperature=2.0,
        seeds=[0, 1]))
    assert not np.array_equal(out[0], out[1])
    again = np.asarray(sample_generate(
        params, _put(mesh, p), mesh, CFG, 10, key, temperature=2.0,
        seeds=[0, 1]))
    np.testing.assert_array_equal(out, again)
