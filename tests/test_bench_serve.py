"""Serving bench harness smoke (tiny preset, CPU, few requests)."""

import numpy as np

from icikit.bench.serve import make_workload, run_bench


def test_workload_is_seeded_and_poisson_shaped():
    w1 = make_workload(8, 10.0, 8, 4, 12, vocab=61, seed=3)
    w2 = make_workload(8, 10.0, 8, 4, 12, vocab=61, seed=3)
    assert len(w1) == 8
    for (o1, p1, n1, s1), (o2, p2, n2, s2) in zip(w1, w2):
        assert o1 == o2 and n1 == n2 and s1 == s2 == 0
        np.testing.assert_array_equal(p1, p2)
    offs = [o for o, _, _, _ in w1]
    assert offs == sorted(offs) and offs[0] > 0
    assert all(4 <= n <= 12 for _, _, n, _ in w1)
    assert make_workload(8, 10.0, 8, 4, 12, vocab=61, seed=4) != w1
    # per-request stream seeds = arrival index when armed
    w3 = make_workload(4, 10.0, 8, 4, 12, vocab=61, seed=3,
                       seed_per_request=True)
    assert [s for _, _, _, s in w3] == [0, 1, 2, 3]


def test_duplicate_prompt_workload_cycles_distinct():
    w = make_workload(6, 10.0, 8, 4, 8, vocab=61, seed=6, distinct=2)
    prompts = [tuple(p) for _, p, _, _ in w]
    assert len(set(prompts)) == 2
    assert prompts[0] == prompts[2] == prompts[4]
    assert prompts[1] == prompts[3] == prompts[5]


def test_repetitive_motif_workload_tiles():
    w = make_workload(3, 10.0, 10, 4, 8, vocab=61, seed=6, motif=4)
    for _, p, _, _ in w:
        np.testing.assert_array_equal(p, np.tile(p[:4], 3)[:10])
    # distinct motifs per request by default
    assert len({tuple(p) for _, p, _, _ in w}) > 1
    import pytest
    with pytest.raises(ValueError, match="exclusive"):
        make_workload(3, 10.0, 10, 4, 8, vocab=61, motif=4,
                      prefix_len=4)


def test_serve_bench_both_modes():
    recs = run_bench("tiny", rows=2, n_requests=5, rate_rps=50.0,
                     prompt_len=8, new_min=4, new_max=8,
                     block_size=4, seed=0, mode="both")
    assert [r["mode"] for r in recs] == ["continuous", "static"]
    cont, stat = recs
    # matched load: same workload, same useful tokens by construction
    assert cont["tokens"] == stat["tokens"] > 0
    assert cont["completed"] == stat["completed"] == 5
    assert cont["failed"] == 0
    for r in recs:
        assert r["kind"] == "serve" and r["backend"]
        assert r["tokens_per_s"] > 0
        assert r["ttft_ms"]["p99"] >= r["ttft_ms"]["p50"] > 0
        assert 0.0 < r["occupancy_mean"] <= 1.0


def test_serve_bench_speculative_mode():
    recs = run_bench("tiny", rows=2, n_requests=4, rate_rps=100.0,
                     prompt_len=8, new_min=4, new_max=8,
                     block_size=4, speculate=3, seed=1,
                     mode="continuous")
    [cont] = recs
    assert cont["speculate"] == 3
    assert cont["completed"] == 4 and cont["failed"] == 0
    # ngram verify windows commit >= 1 token per row-step
    assert cont["tokens_per_step_row"] >= 1.0


def test_shared_prefix_workload_shape():
    w = make_workload(6, 10.0, 12, 4, 8, vocab=61, seed=5,
                      prefix_len=8)
    first = w[0][1]
    for _, p, _, _ in w:
        np.testing.assert_array_equal(p[:8], first[:8])
    # suffixes actually vary
    assert len({tuple(p[8:]) for _, p, _, _ in w}) > 1
    # prefix == prompt -> fully repeated prompts
    w2 = make_workload(4, 10.0, 8, 4, 8, vocab=61, seed=5,
                       prefix_len=8)
    assert len({tuple(p) for _, p, _, _ in w2}) == 1
    import pytest
    with pytest.raises(ValueError, match="prefix_len"):
        make_workload(4, 10.0, 8, 4, 8, vocab=61, prefix_len=9)


def test_serve_bench_prefix_cache_arms_and_identity_audit():
    """The r11 A/B shape at smoke scale: cache-on row records hits +
    a clean identity audit; cache-off row records a cold path. Runs
    the CPU-fp32 protocol — on XLA:CPU the bf16 engine-vs-generate
    comparison diverges for the per-call weight-repack reason the r9
    docs record (pre-existing; the committed rows are fp32)."""
    from icikit.bench.serve import run_bench
    on = run_bench("tiny", rows=2, n_requests=5, rate_rps=100.0,
                   prompt_len=12, new_min=4, new_max=6,
                   block_size=4, seed=3, mode="continuous",
                   compute_dtype="float32",
                   prefix_len=8, prefix_cache=True, prefill_chunk=8,
                   verify=True)[0]
    off = run_bench("tiny", rows=2, n_requests=5, rate_rps=100.0,
                    prompt_len=12, new_min=4, new_max=6,
                    block_size=4, seed=3, mode="continuous",
                    compute_dtype="float32",
                    prefix_len=8, prefix_cache=False, prefill_chunk=8,
                    verify=True)[0]
    assert on["prefix_cache"] and not off["prefix_cache"]
    assert on["prefix"]["hits"] == 5 and on["prefix"]["hit_tokens"] \
        == 5 * 8
    assert off["prefix"]["hits"] == 0
    for r in (on, off):
        assert r["identity_ok"] and r["identity_checked"] == 5
        assert r["completed"] == 5 and r["failed"] == 0


def test_serve_bench_sampled_arm_and_dedup_ledger():
    """The r12 A/B shape at smoke scale: a sampled duplicate-prompt
    arm audits clean against per-seed sample_generate, and the dedup
    ledger (prefill tokens computed + in-flight waiters) responds to
    the knob."""
    common = dict(rows=2, n_requests=4, rate_rps=1000.0,
                  prompt_len=12, new_min=4, new_max=6, block_size=4,
                  seed=7, mode="continuous", compute_dtype="float32",
                  prefill_chunk=4, distinct=1, temperature=0.8,
                  top_p=0.9, seed_per_request=True, verify=True)
    on = run_bench("tiny", **common, inflight_dedup=True)[0]
    off = run_bench("tiny", **common, inflight_dedup=False)[0]
    for r in (on, off):
        assert r["identity_ok"] and r["identity_checked"] == 4
        assert r["completed"] == 4 and r["failed"] == 0
        assert r["temperature"] == 0.8 and r["seed_per_request"]
    assert on["prefix"]["inflight_hits"] >= 1
    assert off["prefix"]["inflight_hits"] == 0
    assert on["prefill_tokens_computed"] < off["prefill_tokens_computed"]
    # duplicate arrivals have a recorded second-arrival TTFT
    assert on["dup_ttft_ms"]["p50"] is not None
