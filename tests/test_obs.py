"""Unified tracing & metrics (`icikit.obs`): the event bus delivers to
every sink and never to none, spans export as a valid Chrome trace
(balanced B/E per thread, monotonic timestamps — the golden-file
checks), the metrics registry snapshots JSON-safe, and the whole layer
costs nothing when disabled."""

import json
import math
import threading
import tracemalloc

import pytest

from icikit import chaos, obs
from icikit.obs import bus, tracer
from icikit.utils.timing import Stopwatch, timeit


@pytest.fixture(autouse=True)
def _obs_fully_disabled():
    """Every test starts and ends with no sinks, no tracer, no
    registry — a leaked global here would silently tax the whole
    suite."""
    assert not bus.enabled(), "sink leaked into test"
    assert tracer.tracing() is None, "tracer leaked into test"
    assert obs.metrics() is None, "registry leaked into test"
    yield
    assert not bus.enabled(), "test leaked a sink"
    assert tracer.tracing() is None, "test leaked a tracer"
    assert obs.metrics() is None, "test leaked a registry"


# -- event bus ------------------------------------------------------

def test_emit_without_sink_is_noop():
    obs.emit("anything", x=1)  # must not raise, must not format


def test_ring_sink_captures_in_order():
    ring = obs.RingSink()
    with bus.installed(ring):
        obs.emit("a", i=0)
        obs.emit("b", i=1)
        obs.emit("a", i=2)
    obs.emit("late", i=3)  # after scope: not captured
    assert [e["event"] for e in ring.events] == ["a", "b", "a"]
    assert [e["i"] for e in ring.of_type("a")] == [0, 2]


def test_event_none_omits_key():
    ring = obs.RingSink()
    with bus.installed(ring):
        obs.emit(None, step=3, loss=1.5)
    assert ring.events == [{"step": 3, "loss": 1.5}]


def test_ring_sink_bounded():
    ring = obs.RingSink(capacity=4)
    with bus.installed(ring):
        for i in range(10):
            obs.emit("e", i=i)
    assert [e["i"] for e in ring.events] == [6, 7, 8, 9]


def test_broken_sink_does_not_stop_delivery():
    class Broken(obs.Sink):
        def write(self, ev):
            raise RuntimeError("boom")

    ring = obs.RingSink()
    with bus.installed(Broken()), bus.installed(ring):
        obs.emit("x")
    assert len(ring.events) == 1


def test_jsonl_sink_strict_json(capsys):
    with bus.installed(obs.JsonlSink("stdout")):
        obs.emit("loss", value=float("nan"), inf=float("inf"), ok=1.5)
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 1
    # strict parser: bare NaN/Infinity would raise here
    ev = json.loads(lines[0], parse_constant=lambda c: pytest.fail(
        f"non-strict JSON constant {c} on the wire"))
    assert ev["value"] == "nan" and ev["inf"] == "inf" and ev["ok"] == 1.5


def test_jsonl_sink_matches_print_json_dumps(capsys):
    """The migrated telemetry must be byte-identical to the historical
    `print(json.dumps(rec))` lines for finite payloads."""
    rec = {"step": 7, "loss": 2.25, "tokens_per_s": 1234.5}
    with bus.installed(obs.JsonlSink("stdout")):
        obs.emit(None, **rec)
    assert capsys.readouterr().out == json.dumps(rec) + "\n"


def test_file_sink_appends_jsonl(tmp_path):
    path = tmp_path / "events.jsonl"
    sink = obs.FileSink(str(path))
    with bus.installed(sink):
        obs.emit("a", i=0)
        obs.emit("b", i=1)
    sink.close()
    sink.write({"event": "late"})  # post-close: dropped, no crash
    evs = [json.loads(l) for l in path.read_text().splitlines()]
    assert [e["event"] for e in evs] == ["a", "b"]


def test_jsonl_sink_filter_drops_only_for_that_sink(capsys):
    """The trainer's record-sink discipline: a filtered sink drops the
    event, other sinks still receive it."""
    ring = obs.RingSink()
    record = obs.JsonlSink("stdout", filter=lambda ev: not str(
        ev.get("event", "")).startswith("chaos."))
    with bus.installed(record), bus.installed(ring):
        obs.emit("chaos.skipped", site="w.0")
        obs.emit(None, step=1, loss=2.0)
    out = capsys.readouterr().out
    assert "chaos.skipped" not in out and '"step": 1' in out
    assert [e.get("event") for e in ring.events] == ["chaos.skipped",
                                                     None]


def test_emit_records_stdout_contract(capsys):
    """The shared CLI record path: historical print(json.dumps) bytes
    on stdout, same records on armed sinks, sink scoped to the call."""
    recs = [{"kind": "r", "i": 0}, {"kind": "r", "i": 1}]
    ring = obs.RingSink()
    with bus.installed(ring):
        obs.emit_records(recs)
    assert capsys.readouterr().out == "".join(
        json.dumps(r) + "\n" for r in recs)
    assert ring.events == recs


def test_json_safe_recurses():
    out = bus.json_safe({"a": [float("nan"), 1.0],
                         "b": {"c": float("-inf")}})
    assert out == {"a": ["nan", 1.0], "b": {"c": "-inf"}}
    assert bus.json_safe((1.0, 2.0)) == [1.0, 2.0]


# -- spans / Chrome trace golden checks -----------------------------

def test_trace_exports_valid_and_nested(tmp_path):
    """The golden-file check: a nested multi-span run exports to a
    trace.json the structural validator fully accepts."""
    with obs.session() as s:
        with obs.span("outer", run=1) as outer:
            with obs.span("inner", chunk=0):
                pass
            with obs.span("inner", chunk=1):
                pass
        obs.instant("tick", n=2)
    path = tmp_path / "trace.json"
    obs.export_trace(str(path), s.trace.snapshot())
    assert obs.validate_trace(str(path)) == []

    trace = json.loads(path.read_text())
    assert trace["traceEvents"]
    names = [(e["ph"], e["name"]) for e in trace["traceEvents"]
             if e["ph"] in "BEi"]
    assert names == [("B", "outer"), ("B", "inner"), ("E", "inner"),
                     ("B", "inner"), ("E", "inner"), ("E", "outer"),
                     ("i", "tick")]
    # children carry the parent's span id; records can join on trace_id
    begins = [e for e in trace["traceEvents"] if e["ph"] == "B"]
    assert outer.trace_id == begins[0]["args"]["trace_id"]
    assert all(b["args"]["parent"] == outer.trace_id
               for b in begins[1:])


def test_trace_timestamps_monotonic_per_thread():
    """Each thread gets its own timeline with monotonic timestamps —
    threads run *sequentially* here on purpose: the OS reuses thread
    idents after a join, and the buffer's synthetic tids must keep the
    dead thread's track separate from its ident-reusing successor."""
    with obs.session(metrics=False) as s:
        def work():
            for i in range(5):
                with obs.span("t.work", i=i):
                    pass
        for _ in range(4):
            t = threading.Thread(target=work)
            t.start()
            t.join()
        with obs.span("main"):
            pass
    events = s.trace.snapshot()
    assert obs.validate_trace(events) == []
    per_tid = {}
    for e in events:
        if "ts" in e:
            per_tid.setdefault(e["tid"], []).append(e["ts"])
    assert len(per_tid) == 5  # 4 workers + main thread, never merged
    for tss in per_tid.values():
        assert tss == sorted(tss)
    named = [e for e in events
             if e["ph"] == "M" and e["name"] == "thread_name"]
    assert len(named) == 5  # one metadata record per timeline


def test_validator_catches_unbalanced_b():
    bad = [{"ph": "B", "name": "x", "pid": 1, "tid": 1, "ts": 0}]
    assert any("unclosed" in p for p in obs.validate_trace(bad))


def test_validator_catches_orphan_e():
    bad = [{"ph": "E", "name": "x", "pid": 1, "tid": 1, "ts": 0}]
    assert any("no open B" in p for p in obs.validate_trace(bad))


def test_validator_catches_nesting_violation():
    bad = [{"ph": "B", "name": "a", "pid": 1, "tid": 1, "ts": 0},
           {"ph": "B", "name": "b", "pid": 1, "tid": 1, "ts": 1},
           {"ph": "E", "name": "a", "pid": 1, "tid": 1, "ts": 2},
           {"ph": "E", "name": "b", "pid": 1, "tid": 1, "ts": 3}]
    assert any("nesting violation" in p for p in obs.validate_trace(bad))


def test_validator_catches_backwards_ts():
    bad = [{"ph": "B", "name": "a", "pid": 1, "tid": 1, "ts": 5},
           {"ph": "E", "name": "a", "pid": 1, "tid": 1, "ts": 3}]
    assert any("backwards" in p for p in obs.validate_trace(bad))


def test_validator_accepts_interleaved_threads():
    ok = [{"ph": "B", "name": "a", "pid": 1, "tid": 1, "ts": 0},
          {"ph": "B", "name": "b", "pid": 1, "tid": 2, "ts": 1},
          {"ph": "E", "name": "a", "pid": 1, "tid": 1, "ts": 2},
          {"ph": "E", "name": "b", "pid": 1, "tid": 2, "ts": 3}]
    assert obs.validate_trace(ok) == []


def test_validator_rejects_garbage():
    assert obs.validate_trace("not json {")
    assert obs.validate_trace(42)
    assert obs.validate_trace({"noTraceEvents": []})
    assert any("bad dur" in p for p in obs.validate_trace(
        [{"ph": "X", "name": "x", "pid": 1, "tid": 1, "ts": 0,
          "dur": -1}]))


def test_chrome_cli_checker(tmp_path, capsys):
    from icikit.obs import chrome
    good = tmp_path / "good.json"
    with obs.session(metrics=False) as s:
        with obs.span("a"):
            pass
    chrome.export(str(good), s.trace.snapshot())
    assert chrome.main([str(good)]) == 0
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"traceEvents": [
        {"ph": "B", "name": "x", "pid": 1, "tid": 1, "ts": 0}]}))
    assert chrome.main([str(bad)]) == 1
    capsys.readouterr()


def test_traced_decorator():
    @obs.traced("deco.fn", tag="t")
    def fn(x):
        return x + 1

    assert fn(1) == 2  # disabled path: plain call
    with obs.session(metrics=False) as s:
        assert fn(2) == 3
    names = [e["name"] for e in s.trace.snapshot() if e["ph"] == "B"]
    assert names == ["deco.fn"]
    assert fn.__name__ == "fn"


def test_export_closes_spans_of_abandoned_threads(tmp_path):
    """A hung straggler the scheduler abandons (join timeout — a
    scenario the farm heals through) dies mid-span; the export must
    still validate, with the synthetic closes marked as such."""
    with obs.session(metrics=False) as s:
        def hang_midspan():
            obs.span("solve.worker", worker=9).__enter__()
        t = threading.Thread(target=hang_midspan)
        t.start()
        t.join()
        with obs.span("main"):
            pass
    raw = s.trace.snapshot()
    assert any("unclosed" in p for p in obs.validate_trace(raw))
    path = tmp_path / "trace.json"
    obs.export_trace(str(path), raw)
    assert obs.validate_trace(str(path)) == []
    evs = json.loads(path.read_text())["traceEvents"]
    synth = [e for e in evs
             if e.get("args", {}).get("closed_by") == "export"]
    assert [e["name"] for e in synth] == ["solve.worker"]


# -- metrics --------------------------------------------------------

def test_metrics_disabled_helpers_are_noops():
    obs.count("x")
    obs.gauge("x", 1.0)
    obs.observe("x", 1.0)
    assert obs.metrics_snapshot() is None


def test_registry_counters_gauges_histograms():
    with obs.session(trace=False) as s:
        obs.count("sched.reissues", 3)
        obs.count("sched.reissues")
        obs.count("sched.deaths", 0)  # registers without moving
        obs.gauge("workers", 7)
        for v in [1.0, 2.0, 3.0, 4.0]:
            obs.observe("step_ms", v)
        snap = s.registry.snapshot()
    assert snap["counters"] == {"sched.deaths": 0, "sched.reissues": 4}
    assert snap["gauges"] == {"workers": 7.0}
    h = snap["histograms"]["step_ms"]
    assert h["count"] == 4 and h["sum"] == 10.0
    assert h["min"] == 1.0 and h["max"] == 4.0 and h["mean"] == 2.5
    assert h["p50"] in (2.0, 3.0) and h["p99"] == 4.0
    json.dumps(snap, allow_nan=False)  # snapshot is JSON-safe


def test_histogram_decimation_bounded_exact_aggregates():
    h = obs.Registry().histogram("x")
    n = 20_000
    for i in range(n):
        h.observe(float(i))
    assert h.count == n and h.min == 0.0 and h.max == float(n - 1)
    assert h.total == sum(range(n))
    assert len(h._sample) < 4096  # bounded memory
    # the stride-decimated sample still spans the stream evenly
    assert abs(h.percentile(50) - n / 2) < n * 0.05


def test_empty_histogram_summary():
    h = obs.Registry().histogram("x")
    s = h.summary()
    assert s["count"] == 0 and s["p50"] is None and s["mean"] is None


# -- session / env spec ---------------------------------------------

def test_session_restores_previous_state():
    outer = tracer.start_tracing()
    try:
        with obs.session(metrics=False) as s:
            assert tracer.tracing() is s.trace is not outer
        assert tracer.tracing() is outer
    finally:
        tracer.stop_tracing()


def test_parse_spec_defaults_and_custom():
    d = obs.parse_spec("1")
    assert d == {"jsonl": "stderr", "trace": "trace.json",
                 "metrics": "obs_metrics.json", "mirror": False}
    d = obs.parse_spec("trace=/tmp/t.json;jsonl=off;mirror=1")
    assert d["trace"] == "/tmp/t.json" and d["jsonl"] == "off"
    assert d["mirror"] is True and d["metrics"] == "obs_metrics.json"
    with pytest.raises(ValueError):
        obs.parse_spec("bogus=1")
    with pytest.raises(ValueError):
        obs.parse_spec("trace")  # no '='


# -- zero-overhead contract -----------------------------------------

def test_disabled_span_is_shared_singleton():
    a = obs.span("x", big=list(range(100)))
    b = obs.span("y")
    assert a is b is obs.NOOP_SPAN
    with a as sp:
        assert sp.trace_id is None


def test_disabled_paths_allocate_nothing():
    """The probe discipline shared with icikit.chaos: no sink and no
    tracer means no allocation on the hot path."""
    def hot():
        for _ in range(300):
            with obs.span("s"):
                pass
            obs.emit("e", a=1)
            obs.count("c")
            obs.observe("h", 1.0)

    hot()  # warm up any lazy internals
    tracemalloc.start()
    hot()
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert peak < 4096, f"disabled obs path allocated {peak} B"


# -- timing emit hooks ----------------------------------------------

def test_stopwatch_emit_hook():
    got = []
    w = Stopwatch(emit=got.append)
    a, b = w(), w()
    assert got == [a, b] and all(v >= 0 for v in got)
    # default stays hook-free
    assert Stopwatch()() >= 0


def test_timeit_emit_feeds_metrics():
    import jax.numpy as jnp
    with obs.session(trace=False) as s:
        res = timeit(lambda: jnp.zeros(8), runs=3, warmup=1,
                     emit=lambda sec: obs.observe("bench.run_ms",
                                                  sec * 1e3))
        snap = s.registry.snapshot()
    h = snap["histograms"]["bench.run_ms"]
    assert h["count"] == res.runs == 3
    assert math.isclose(h["sum"], res.total_s * 1e3, rel_tol=1e-6)


# -- chaos events ---------------------------------------------------

def test_chaos_probes_emit_fired_and_skipped_events():
    ring = obs.RingSink()
    plan = chaos.FaultPlan(schedule={"delay:w.0": (1,)}, delay_s=0.0)
    with bus.installed(ring), chaos.inject(plan):
        chaos.maybe_delay("w.0")  # call 0: skipped
        chaos.maybe_delay("w.0")  # call 1: fires
    fired = ring.of_type("chaos.fired")
    skipped = ring.of_type("chaos.skipped")
    assert [(e["kind"], e["site"], e["call"]) for e in fired] == [
        ("delay", "w.0", 1)]
    assert [(e["kind"], e["site"], e["call"]) for e in skipped] == [
        ("delay", "w.0", 0)]
    assert all(e["seed"] == plan.seed for e in fired + skipped)


def test_chaos_fired_lands_on_trace_timeline():
    plan = chaos.FaultPlan(schedule={"delay:w.0": (0,)}, delay_s=0.0)
    with obs.session(metrics=False) as s, chaos.inject(plan):
        with obs.span("pull"):
            chaos.maybe_delay("w.0")
    events = s.trace.snapshot()
    assert obs.validate_trace(events) == []
    insts = [e for e in events if e["ph"] == "i"]
    assert [e["name"] for e in insts] == ["chaos.fired"]
    assert insts[0]["args"]["site"] == "w.0"


# -- integration: the dynamic scheduler under obs -------------------

def test_solve_dynamic_obs_wiring():
    """One healed solve run yields a valid trace, the scheduler
    counters (including zero-valued ones), and lease/death events —
    the acceptance criteria's scheduler half, in-process."""
    from icikit.models.solitaire.dataset import generate_dataset
    from icikit.models.solitaire.scheduler import solve_dynamic

    ring = obs.RingSink()
    plan = chaos.FaultPlan(schedule={"die:solitaire.worker.0": (0,)})
    with obs.session(ring) as s, chaos.inject(plan):
        with pytest.warns(RuntimeWarning, match="worker 0"):
            rep = solve_dynamic(generate_dataset(16, "easy", seed=3),
                                chunk_size=4)
        events = s.trace.snapshot()
        snap = s.registry.snapshot()
    assert rep.n_deaths == 1 and rep.n_reissues > 0
    assert obs.validate_trace(events) == []
    names = {e["name"] for e in events if e["ph"] == "B"}
    assert {"solve.dynamic", "solve.worker", "solve.pull",
            "solve.chunk"} <= names
    c = snap["counters"]
    assert c["scheduler.deaths"] == 1
    assert c["scheduler.reissues"] == rep.n_reissues
    assert c["scheduler.commits"] >= 4
    assert "scheduler.lease_expired" in c  # registered even at 0
    deaths = ring.of_type("scheduler.worker_death")
    assert len(deaths) == 1 and deaths[0]["reissued_chunks"]
    assert ring.of_type("scheduler.drained")[0]["reissues"] == \
        rep.n_reissues
