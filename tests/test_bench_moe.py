"""MoE study bench: capacity-grid semantics and the dispatch path."""

import jax
import pytest

from icikit.bench.moe import (capacity_grid, dispatch_bench,
                              render_markdown, routing_drop_stats)


def test_drop_monotone_in_capacity():
    """More capacity never drops more tokens; sub-unit capacity must
    drop at least the arithmetic deficit (T tokens, cf*T slots)."""
    rows = [routing_drop_stats(2048, 64, 8, cf, skew=0.0)
            for cf in (0.5, 1.0, 1.5)]
    drops = [r["drop_frac"] for r in rows]
    assert drops[0] >= drops[1] >= drops[2]
    assert drops[0] >= 0.5 - 1e-6  # cf=0.5 holds half the tokens
    assert drops[2] <= 0.02        # uniform routing fits at cf=1.5


def test_skew_increases_drop_and_imbalance():
    base = routing_drop_stats(2048, 64, 8, 1.25, skew=0.0)
    skewed = routing_drop_stats(2048, 64, 8, 1.25, skew=4.0)
    assert skewed["drop_frac"] > base["drop_frac"]
    assert skewed["imbalance"] > base["imbalance"] > 0.9


def test_capacity_grid_shape():
    recs = capacity_grid(n_tokens=512, d_model=32, experts=(4,),
                         cfs=(1.0, 2.0), skews=(0.0,))
    assert len(recs) == 2
    assert all(r["kind"] == "moe_capacity" for r in recs)


def test_dispatch_bench_runs_on_mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device simulated mesh")
    recs = dispatch_bench(p=8, experts=(8,), algorithms=("xla",),
                          b=2, s=16, d_model=32, d_ff=64, runs=2)
    assert recs and recs[0]["tokens_per_s"] > 0
    text = render_markdown([], recs)
    assert "Dispatch throughput" in text
