"""Oracle tests: located reductions, distributed top-k, point-to-point."""

from functools import partial

import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from icikit.parallel import (
    allreduce_loc,
    send_to,
    sendrecv_shift,
    sendrecv_xor,
    top_k_dist,
)
from icikit.parallel.shmap import shard_map
from icikit.utils.mesh import shard_along


def _data(p, n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(-10_000, 10_000, (p, n)).astype(np.int32)


@pytest.mark.parametrize("op,npfn", [("maxloc", np.argmax),
                                     ("minloc", np.argmin)])
def test_allreduce_loc(mesh8, op, npfn):
    data = _data(8, 32, seed=1)
    x = shard_along(jnp.asarray(data), mesh8)
    v, i = allreduce_loc(x, mesh8, op=op)
    flat = data.reshape(-1)
    assert int(i) == npfn(flat)
    assert int(v) == flat[npfn(flat)]


def test_allreduce_loc_tie_lowest_index(mesh8):
    data = np.zeros((8, 4), np.int32)
    data[2, 1] = 7
    data[5, 3] = 7  # duplicate max, higher global index
    x = shard_along(jnp.asarray(data), mesh8)
    v, i = allreduce_loc(x, mesh8, op="maxloc")
    assert int(v) == 7 and int(i) == 2 * 4 + 1


def test_allreduce_loc_validates(mesh8):
    x = shard_along(jnp.zeros((8, 4), jnp.int32), mesh8)
    with pytest.raises(ValueError, match="maxloc"):
        allreduce_loc(x, mesh8, op="sum")


@pytest.mark.parametrize("largest", [True, False])
@pytest.mark.parametrize("k", [1, 3, 8])
def test_top_k_dist(mesh8, k, largest):
    data = _data(8, 16, seed=2)
    x = shard_along(jnp.asarray(data), mesh8)
    v, i = top_k_dist(x, mesh8, k, largest=largest)
    flat = data.reshape(-1)
    order = np.argsort(-flat if largest else flat, kind="stable")[:k]
    np.testing.assert_array_equal(np.asarray(v), flat[order])
    # indices must point at the returned values (ties may permute ids)
    np.testing.assert_array_equal(flat[np.asarray(i)], np.asarray(v))


def test_top_k_dist_validates(mesh8):
    x = shard_along(jnp.zeros((8, 4), jnp.int32), mesh8)
    with pytest.raises(ValueError, match="exceeds the per-device"):
        top_k_dist(x, mesh8, k=5)
    with pytest.raises(ValueError, match="k must be"):
        top_k_dist(x, mesh8, k=0)


def test_pt2pt_primitives(mesh8):
    p = 8
    data = _data(p, 4, seed=3)
    x = shard_along(jnp.asarray(data), mesh8)

    def body(fn, b):
        return fn(b[0])[None]

    def run(per_block):
        return np.asarray(shard_map(
            partial(body, per_block), mesh=mesh8, in_specs=P("p"),
            out_specs=P("p"))(x))

    out = run(lambda blk: sendrecv_shift(blk, "p", p, 2))
    np.testing.assert_array_equal(out, np.roll(data, 2, axis=0))

    out = run(lambda blk: sendrecv_xor(blk, "p", p, 3))
    np.testing.assert_array_equal(out, data[np.arange(p) ^ 3])

    # targeted send 0 -> 5: receiver sees the payload, idle devices zeros
    out = run(lambda blk: send_to(blk, "p", [(0, 5)]))
    np.testing.assert_array_equal(out[5], data[0])
    assert (out[np.arange(p) != 5] == 0).all()


def test_reduceloc_float(mesh8):
    rng = np.random.default_rng(4)
    data = rng.standard_normal((8, 16)).astype(np.float32)
    x = shard_along(jnp.asarray(data), mesh8)
    v, i = allreduce_loc(x, mesh8, op="minloc")
    flat = data.reshape(-1)
    assert int(i) == np.argmin(flat)
    np.testing.assert_allclose(float(v), flat.min())


def test_top_k_min_direction_int_min(mesh8):
    """The signed minimum must survive bottom-k (a negation-based
    implementation overflows it away)."""
    data = np.full((8, 4), 5, np.int32)
    data[3, 2] = np.iinfo(np.int32).min
    x = shard_along(jnp.asarray(data), mesh8)
    v, i = top_k_dist(x, mesh8, 1, largest=False)
    assert int(v[0]) == np.iinfo(np.int32).min
    assert int(i[0]) == 3 * 4 + 2


def test_block_shape_validation(mesh8):
    x = shard_along(jnp.zeros((16, 4), jnp.int32), mesh8)
    with pytest.raises(ValueError, match="one .* block per device"):
        allreduce_loc(x, mesh8)
    with pytest.raises(ValueError, match="one .* block per device"):
        top_k_dist(x, mesh8, 1)


def test_sendrecv_xor_validates(mesh8):
    from icikit.utils.mesh import UnsupportedMeshError, make_mesh
    mesh6 = make_mesh(6)
    data = _data(6, 4, seed=5)
    x = shard_along(jnp.asarray(data), mesh6)

    def run():
        return shard_map(
            lambda b: sendrecv_xor(b[0], "p", 6, 2)[None],
            mesh=mesh6, in_specs=P("p"), out_specs=P("p"))(x)

    with pytest.raises(UnsupportedMeshError, match="power-of-2"):
        run()


@pytest.mark.parametrize("periodic", [True, False])
def test_halo_exchange(mesh8, periodic):
    from icikit.parallel import halo_exchange
    p, n, w = 8, 6, 2
    data = _data(p, n, seed=6)
    x = shard_along(jnp.asarray(data), mesh8)

    def body(b):
        lh, rh = halo_exchange(b[0], "p", p, w, periodic=periodic)
        return lh[None], rh[None]

    lh, rh = shard_map(body, mesh=mesh8, in_specs=P("p"),
                       out_specs=(P("p"), P("p")))(x)
    lh, rh = np.asarray(lh), np.asarray(rh)
    for d in range(p):
        want_l = data[(d - 1) % p, -w:]
        want_r = data[(d + 1) % p, :w]
        if not periodic and d == 0:
            want_l = np.zeros((w, ), np.int32)
        if not periodic and d == p - 1:
            want_r = np.zeros((w, ), np.int32)
        np.testing.assert_array_equal(lh[d], want_l)
        np.testing.assert_array_equal(rh[d], want_r)


def test_halo_width_validated(mesh8):
    from icikit.parallel import halo_exchange
    data = _data(8, 4, seed=7)
    x = shard_along(jnp.asarray(data), mesh8)
    with pytest.raises(ValueError, match="halo width"):
        shard_map(lambda b: halo_exchange(b[0], "p", 8, 5)[0][None],
                  mesh=mesh8, in_specs=P("p"), out_specs=P("p"))(x)


def test_barrier_is_consumable(mesh8):
    from icikit.parallel import barrier
    data = _data(8, 4, seed=8)
    x = shard_along(jnp.asarray(data), mesh8)
    out = shard_map(lambda b: (b[0] + barrier("p"))[None], mesh=mesh8,
                    in_specs=P("p"), out_specs=P("p"))(x)
    np.testing.assert_array_equal(np.asarray(out), data)
