"""Figure rendering (``icikit.bench.figs``): the committed PNGs must be
regenerable from the committed jsonl records with no hardware."""

from __future__ import annotations

import json
import os


def _write(path, rows):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


def test_render_all_from_records(tmp_path):
    import matplotlib
    matplotlib.use("Agg")
    from icikit.bench.figs import render_all
    sc = tmp_path / "scaling.jsonl"
    ns = tmp_path / "northstar.jsonl"
    lc = tmp_path / "longcontext.jsonl"
    _write(sc, [{"family": "allgather", "algorithm": a, "p": p,
                 "msize": m, "best_s": 1e-4 * m * p / 64}
                for a in ("ring", "xla") for p in (2, 8)
                for m in (1, 65536)]
           + [{"family": "alltoall", "algorithm": "hypercube", "p": 8,
               "msize": 16, "best_s": 1e-4},
              {"family": "allreduce", "algorithm": "ring", "p": 4,
               "msize": 65536, "best_s": 2e-3}])
    _write(ns, [{"kind": "sort", "algorithm": "bitonic", "p": 1,
                 "n": 1 << 20, "distribution": "uniform",
                 "keys_per_s": 1e8},
                {"kind": "sort", "algorithm": "sample", "p": 1,
                 "n": 1 << 20, "distribution": "uniform",
                 "keys_per_s": 5e7}])
    _write(lc, [{"impl": "flash", "mode": "fwd", "seq": 32768,
                 "d_head": 64, "tflops": 66.0, "verified": True},
                {"impl": "flash", "mode": "fwd", "seq": 32768,
                 "d_head": 64, "tflops": 999.0, "verified": True},
                {"impl": "flash", "mode": "fwdbwd", "seq": 32768,
                 "d_head": 128, "tflops": 170.0, "verified": True}])
    out = render_all(outdir=str(tmp_path / "figs"), scaling=str(sc),
                     northstar=str(ns), longcontext=str(lc))
    names = {os.path.basename(p) for p in out}
    assert "scaling_allgather_msize_p8.png" in names
    assert "sort_throughput.png" in names
    assert "longcontext_tflops.png" in names
    for p in out:
        assert os.path.getsize(p) > 10_000  # real rendered images


def test_missing_records_are_skipped(tmp_path):
    import matplotlib
    matplotlib.use("Agg")
    from icikit.bench.figs import render_all
    out = render_all(outdir=str(tmp_path / "figs"),
                     scaling=str(tmp_path / "none.jsonl"),
                     northstar=str(tmp_path / "none.jsonl"),
                     longcontext=str(tmp_path / "none.jsonl"),
                     sort_scaling=str(tmp_path / "none.jsonl"))
    assert out == []


def test_artifact_filter_excludes_impossible_readings(tmp_path):
    """Readings above the measured matmul ceiling are timing artifacts
    and must not enter the best-of curves."""
    from icikit.bench.figs import _TFLOPS_CEILING, fig_longcontext
    import matplotlib
    matplotlib.use("Agg")
    rows = [{"impl": "flash", "mode": "fwd", "seq": 16384, "d_head": 128,
             "tflops": 731.0, "verified": True},
            {"impl": "flash", "mode": "fwd", "seq": 16384, "d_head": 128,
             "tflops": 150.0, "verified": True}]
    assert rows[0]["tflops"] > _TFLOPS_CEILING
    path = fig_longcontext(rows, str(tmp_path))
    assert path and os.path.getsize(path) > 10_000