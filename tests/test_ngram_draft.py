"""n-gram drafter: proposal rules + token identity under verify.

The proposer is pure guesswork by contract — these tests pin (a) the
matching rule on hand-built sequences and (b) the only property that
matters downstream: ``speculative_generate(..., drafter="ngram")``
stays greedy-token-identical to ``greedy_generate`` regardless of what
was proposed.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from icikit.models.transformer import (
    TransformerConfig,
    greedy_generate,
    init_params,
    speculative_generate,
)
from icikit.models.transformer.model import make_model_mesh
from icikit.serve.ngram_draft import ngram_propose, ngram_propose_host


def _prop(seq, valid, k, n=3):
    return np.asarray(ngram_propose(
        jnp.asarray(seq, jnp.int32)[None],
        jnp.asarray([valid], jnp.int32), k, n))[0]


def test_longest_suffix_match_proposes_continuation():
    # suffix ...7,8 last occurred at positions 1,2 -> propose 9, 4
    seq = [7, 8, 9, 4, 5, 7, 8, 0, 0, 0]
    np.testing.assert_array_equal(_prop(seq, valid=7, k=3), [9, 4])


def test_prefers_latest_occurrence_on_ties():
    # 1-gram suffix [5]: occurs at 0 and 3; latest (3) wins -> 6, 7
    seq = [5, 2, 3, 5, 6, 7, 5, 0]
    np.testing.assert_array_equal(_prop(seq, valid=7, k=3, n=1), [6, 7])


def test_longer_match_beats_later_shorter_match():
    # suffix [2, 3]: 2-gram match ends at 1 -> 8; a later 1-gram match
    # of [3] alone ends at 4 but loses to the longer match
    seq = [2, 3, 8, 3, 9, 2, 3, 0]
    got = _prop(seq, valid=7, k=2, n=3)
    np.testing.assert_array_equal(got, [8])


def test_no_match_falls_back_to_last_token():
    seq = [1, 2, 3, 4, 5, 6, 0, 0]
    np.testing.assert_array_equal(_prop(seq, valid=6, k=3), [6, 6])


def test_short_valid_is_safe():
    # fewer than 2 committed tokens: nothing to match, fallback fires,
    # proposals stay valid token ids (embedding-gather safe)
    out = _prop([9, 0, 0, 0], valid=1, k=4)
    assert out.shape == (3,) and (out >= 0).all()


def test_host_wrapper_matches_device():
    rng = np.random.default_rng(0)
    seq = rng.integers(0, 7, (3, 16)).astype(np.int32)
    valid = np.asarray([16, 9, 2], np.int32)
    a = ngram_propose_host(seq, valid, 4, 3)
    b = np.asarray(ngram_propose(jnp.asarray(seq), jnp.asarray(valid),
                                 4, 3))
    np.testing.assert_array_equal(a, b)


def test_propose_validates_k_and_n():
    with pytest.raises(ValueError, match="k must be"):
        ngram_propose(jnp.zeros((1, 4), jnp.int32),
                      jnp.ones((1,), jnp.int32), k=1)
    with pytest.raises(ValueError, match="n must be"):
        ngram_propose(jnp.zeros((1, 4), jnp.int32),
                      jnp.ones((1,), jnp.int32), k=2, n=0)


CFG = TransformerConfig(vocab=61, d_model=32, n_heads=2, d_head=8,
                        d_ff=64, n_layers=2, max_seq=64,
                        compute_dtype="float32")


def _setup(cfg=CFG, b=2, s=8, dp=1, tp=1):
    mesh = make_model_mesh(dp=dp, tp=tp, sp=1)
    params = init_params(jax.random.key(0), cfg, mesh)
    rng = np.random.default_rng(0)
    pd = jnp.asarray(rng.integers(0, cfg.vocab, (b, s)), jnp.int32)
    return mesh, params, pd


@pytest.mark.parametrize("k", [2, 4])
def test_ngram_drafter_token_identity(k):
    mesh, params, pd = _setup()
    base = np.asarray(greedy_generate(params, pd, mesh, CFG, 12))
    got, st = speculative_generate(params, pd, mesh, CFG, 12, k=k,
                                   drafter="ngram", return_stats=True)
    np.testing.assert_array_equal(np.asarray(got), base)
    assert st["drafter"] == "ngram"
    assert 0.0 <= st["acceptance_rate"] <= 1.0


def test_ngram_drafter_identity_repetitive_prompt():
    """A repetitive prompt is the n-gram drafter's best case — and the
    case where a correctness bug (proposals leaking into commits)
    would actually bite. Identity must hold with high acceptance
    plumbing engaged."""
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    pd = jnp.asarray(np.tile([3, 5, 7, 9], 4)[None], jnp.int32)
    base = np.asarray(greedy_generate(params, pd, mesh, CFG, 16))
    got = np.asarray(speculative_generate(params, pd, mesh, CFG, 16,
                                          k=4, drafter="ngram"))
    np.testing.assert_array_equal(got, base)


def test_ngram_drafter_identity_dp_tp_rope():
    import dataclasses
    cfg = dataclasses.replace(CFG, n_heads=4, pos_encoding="rope")
    mesh, params, pd = _setup(cfg, b=4, dp=2, tp=2)
    base = np.asarray(greedy_generate(params, pd, mesh, cfg, 10))
    got = np.asarray(speculative_generate(params, pd, mesh, cfg, 10,
                                          k=3, drafter="ngram"))
    np.testing.assert_array_equal(got, base)
