"""n-gram drafter: proposal rules + token identity under verify.

The proposer is pure guesswork by contract — these tests pin (a) the
matching rule on hand-built sequences and (b) the only property that
matters downstream: ``speculative_generate(..., drafter="ngram")``
stays greedy-token-identical to ``greedy_generate`` regardless of what
was proposed.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from icikit.models.transformer import (
    TransformerConfig,
    greedy_generate,
    init_params,
    speculative_generate,
)
from icikit.models.transformer.model import make_model_mesh
from icikit.serve.ngram_draft import ngram_propose, ngram_propose_host


def _prop(seq, valid, k, n=3):
    return np.asarray(ngram_propose(
        jnp.asarray(seq, jnp.int32)[None],
        jnp.asarray([valid], jnp.int32), k, n))[0]


def test_longest_suffix_match_proposes_continuation():
    # suffix ...7,8 last occurred at positions 1,2 -> propose 9, 4
    seq = [7, 8, 9, 4, 5, 7, 8, 0, 0, 0]
    np.testing.assert_array_equal(_prop(seq, valid=7, k=3), [9, 4])


def test_prefers_latest_occurrence_on_ties():
    # 1-gram suffix [5]: occurs at 0 and 3; latest (3) wins -> 6, 7
    seq = [5, 2, 3, 5, 6, 7, 5, 0]
    np.testing.assert_array_equal(_prop(seq, valid=7, k=3, n=1), [6, 7])


def test_longer_match_beats_later_shorter_match():
    # suffix [2, 3]: 2-gram match ends at 1 -> 8; a later 1-gram match
    # of [3] alone ends at 4 but loses to the longer match
    seq = [2, 3, 8, 3, 9, 2, 3, 0]
    got = _prop(seq, valid=7, k=2, n=3)
    np.testing.assert_array_equal(got, [8])


def test_no_match_falls_back_to_last_token():
    seq = [1, 2, 3, 4, 5, 6, 0, 0]
    np.testing.assert_array_equal(_prop(seq, valid=6, k=3), [6, 6])


def test_short_valid_is_safe():
    # fewer than 2 committed tokens: nothing to match, fallback fires,
    # proposals stay valid token ids (embedding-gather safe)
    out = _prop([9, 0, 0, 0], valid=1, k=4)
    assert out.shape == (3,) and (out >= 0).all()


def test_host_wrapper_matches_device():
    rng = np.random.default_rng(0)
    seq = rng.integers(0, 7, (3, 16)).astype(np.int32)
    valid = np.asarray([16, 9, 2], np.int32)
    a = ngram_propose_host(seq, valid, 4, 3)
    b = np.asarray(ngram_propose(jnp.asarray(seq), jnp.asarray(valid),
                                 4, 3))
    np.testing.assert_array_equal(a, b)


def test_propose_validates_k_and_n():
    with pytest.raises(ValueError, match="k must be"):
        ngram_propose(jnp.zeros((1, 4), jnp.int32),
                      jnp.ones((1,), jnp.int32), k=1)
    with pytest.raises(ValueError, match="n must be"):
        ngram_propose(jnp.zeros((1, 4), jnp.int32),
                      jnp.ones((1,), jnp.int32), k=2, n=0)


CFG = TransformerConfig(vocab=61, d_model=32, n_heads=2, d_head=8,
                        d_ff=64, n_layers=2, max_seq=64,
                        compute_dtype="float32")


def _setup(cfg=CFG, b=2, s=8, dp=1, tp=1):
    mesh = make_model_mesh(dp=dp, tp=tp, sp=1)
    params = init_params(jax.random.key(0), cfg, mesh)
    rng = np.random.default_rng(0)
    pd = jnp.asarray(rng.integers(0, cfg.vocab, (b, s)), jnp.int32)
    return mesh, params, pd


@pytest.mark.parametrize("k", [2, 4])
def test_ngram_drafter_token_identity(k):
    mesh, params, pd = _setup()
    base = np.asarray(greedy_generate(params, pd, mesh, CFG, 12))
    got, st = speculative_generate(params, pd, mesh, CFG, 12, k=k,
                                   drafter="ngram", return_stats=True)
    np.testing.assert_array_equal(np.asarray(got), base)
    assert st["drafter"] == "ngram"
    assert 0.0 <= st["acceptance_rate"] <= 1.0


def test_ngram_drafter_identity_repetitive_prompt():
    """A repetitive prompt is the n-gram drafter's best case — and the
    case where a correctness bug (proposals leaking into commits)
    would actually bite. Identity must hold with high acceptance
    plumbing engaged."""
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    pd = jnp.asarray(np.tile([3, 5, 7, 9], 4)[None], jnp.int32)
    base = np.asarray(greedy_generate(params, pd, mesh, CFG, 16))
    got = np.asarray(speculative_generate(params, pd, mesh, CFG, 16,
                                          k=4, drafter="ngram"))
    np.testing.assert_array_equal(got, base)


def test_ngram_drafter_identity_dp_tp_rope():
    import dataclasses
    cfg = dataclasses.replace(CFG, n_heads=4, pos_encoding="rope")
    mesh, params, pd = _setup(cfg, b=4, dp=2, tp=2)
    base = np.asarray(greedy_generate(params, pd, mesh, cfg, 10))
    got = np.asarray(speculative_generate(params, pd, mesh, cfg, 10,
                                          k=3, drafter="ngram"))
    np.testing.assert_array_equal(got, base)


# -- ranked-alternatives APIs (round 14 tree drafting) ----------------

def _prop_b(seq, valid, k, n=3, nb=2):
    from icikit.serve.ngram_draft import ngram_propose_b
    return np.asarray(ngram_propose_b(
        jnp.asarray(seq, jnp.int32)[None],
        jnp.asarray([valid], jnp.int32), k, n, nb))[0]


def test_propose_b_rank0_is_the_1way_proposal():
    """Column 0 of the b-way matcher is bitwise the argmax matcher —
    the b=1 tree path really is the chain path's drafting."""
    rng = np.random.default_rng(3)
    for _ in range(8):
        seq = rng.integers(0, 5, 24).tolist()
        v = rng.integers(3, 24)
        one = _prop(seq, valid=int(v), k=4)
        many = _prop_b(seq, valid=int(v), k=4, nb=3)
        np.testing.assert_array_equal(many[:, 0], one)


def test_propose_b_ranks_distinct_matches():
    # suffix [7, 8]: best (2-gram) match ends at 2 -> continue 9, 4;
    # rank 1 is the next-best scored end position (the later 1-gram
    # match of [8] at position 6 -> continue 5, 7)
    seq = [7, 8, 9, 4, 5, 8, 5, 7, 8, 0, 0, 0]
    got = _prop_b(seq, valid=9, k=3, nb=2)
    np.testing.assert_array_equal(got[:, 0], [9, 4])
    assert got.shape == (2, 2)
    # rank 1 comes from a DIFFERENT match end than rank 0
    assert not np.array_equal(got[:, 1], got[:, 0])


def test_propose_b_rank_stability():
    """Same buffer -> same ranked output, call after call (the rank
    score has no ties by construction: position breaks them)."""
    rng = np.random.default_rng(4)
    seq = rng.integers(0, 4, (2, 32)).astype(np.int32)
    valid = np.asarray([30, 17], np.int32)
    from icikit.serve.ngram_draft import ngram_propose_b_host
    a = ngram_propose_b_host(seq, valid, 4, 3, 3)
    b = ngram_propose_b_host(seq, valid, 4, 3, 3)
    np.testing.assert_array_equal(a, b)
    assert a.shape == (2, 3, 3)


def test_propose_b_fallback_ranks_are_valid_tokens():
    # no match at all: every rank falls back to the last token
    got = _prop_b([1, 2, 3, 4, 5, 6, 0, 0], valid=6, k=3, nb=3)
    np.testing.assert_array_equal(got, np.full((2, 3), 6))


def test_propose_b_validates_args():
    from icikit.serve.ngram_draft import ngram_propose_b
    with pytest.raises(ValueError, match="nb must be"):
        ngram_propose_b(jnp.zeros((1, 4), jnp.int32),
                        jnp.ones((1,), jnp.int32), k=2, nb=0)
    with pytest.raises(ValueError, match="exceeds the token buffer"):
        ngram_propose_b(jnp.zeros((1, 4), jnp.int32),
                        jnp.ones((1,), jnp.int32), k=2, nb=5)


def test_suffix_automaton_top_b_rank0_is_propose():
    from icikit.serve.ngram_draft import SuffixAutomaton
    rng = np.random.default_rng(5)
    sam = SuffixAutomaton()
    for t in rng.integers(0, 6, 64):
        sam.feed(int(t))
    for m in (1, 3, 5):
        top = sam.top_b(m, 3)
        np.testing.assert_array_equal(top[:, 0], sam.propose(m))
        assert top.shape == (m, 3)


def test_suffix_automaton_top_b_rank_stability():
    """Deterministic pure function of the fed stream: a fresh
    automaton fed the same tokens ranks identically, and repeated
    calls do not perturb the matcher state."""
    from icikit.serve.ngram_draft import SuffixAutomaton
    rng = np.random.default_rng(6)
    stream = rng.integers(0, 5, 80).tolist()
    sam1, sam2 = SuffixAutomaton(), SuffixAutomaton()
    for t in stream:
        sam1.feed(t)
        sam2.feed(t)
    a = sam1.top_b(4, 3)
    b = sam1.top_b(4, 3)      # idempotent
    c = sam2.top_b(4, 3)      # fresh build
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(a, c)
    assert sam1.match_len == sam2.match_len


def test_suffix_automaton_top_b_offers_seen_continuations():
    # stream: "1 2 3 ... 1 2 4 ... 1 2" — after the final "1 2" the
    # matched factor has been followed by BOTH 3 and 4; rank 0 is the
    # canonical occurrence's continuation, and the other observed
    # continuation must appear among the alternatives
    from icikit.serve.ngram_draft import SuffixAutomaton
    sam = SuffixAutomaton()
    for t in [1, 2, 3, 9, 1, 2, 4, 9, 1, 2]:
        sam.feed(t)
    top = sam.top_b(1, 3)
    assert set(top[0]) >= {3, 4}


def test_suffix_automaton_top_b_cost_is_stream_length_free():
    """O(1)/token: the transitions examined per call are bounded by
    the alphabet, not the stream length — feeding 10x more tokens
    must not grow the per-call work (the satellite's cost pin)."""
    from icikit.serve.ngram_draft import SuffixAutomaton
    rng = np.random.default_rng(7)

    def ops_at(n_tokens):
        sam = SuffixAutomaton()
        for t in rng.integers(0, 8, n_tokens):
            sam.feed(int(t))
        sam.top_b(4, 3)
        return sam.last_topb_ops

    short, long_ = ops_at(100), ops_at(1000)
    # bound: (1 + link hops) states/depth x alphabet, never O(stream)
    assert long_ <= 2 * short + 5 * 8 * 4, (short, long_)


def test_tree_drafter_token_identity():
    """Proposals (ranked or not) never change tokens: tree-drafted
    speculative output stays greedy-identical for both zero-cost
    drafters (the full drafter × branch grid runs in
    tests/test_tree_spec.py)."""
    mesh, params, pd = _setup(b=2)
    base = np.asarray(greedy_generate(params, pd, mesh, CFG, 10))
    for drafter, nb in (("ngram", 2), ("shared", 3)):
        got = np.asarray(speculative_generate(
            params, pd, mesh, CFG, 10, k=3, drafter=drafter,
            tree_branch=nb))
        np.testing.assert_array_equal(got, base)
