"""Flash attention vs the dense oracle: forward and gradients, causal
and full, fp32 and bf16, plus the fallback shapes (SURVEY.md §5.7 —
the within-chip analog of the ring schedule's online softmax)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from icikit.models.attention.dense import dense_attention
from icikit.ops.flash_attention import _pick_block, flash_attention


def _mk(b, s, h, d, dtype, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    return tuple(jax.random.normal(k, (b, s, h, d), dtype) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("s", [64, 192, 384])  # 1 q block / 1 / 3 (nq > 1)
def test_forward_matches_dense(causal, s):
    q, k, v = _mk(2, s, 2, 32, jnp.float32)
    got = flash_attention(q, k, v, causal=causal)
    want = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(got, want, atol=2e-6, rtol=2e-6)


@pytest.mark.parametrize("causal", [False, True])
#             fused single-block bwd / tiled bq=96 bk=32 / nq=3 tiled
@pytest.mark.parametrize("s", [128, 96, 384])
def test_grads_match_dense(causal, s):
    q, k, v = _mk(1, s, 2, 16, jnp.float32, seed=1)

    def loss(fn, q, k, v):
        out = fn(q, k, v, causal=causal)
        return jnp.sum(out * jnp.cos(out))  # non-trivial cotangent

    g_flash = jax.grad(lambda q, k, v: loss(
        flash_attention, q, k, v), argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(lambda q, k, v: loss(
        dense_attention, q, k, v), argnums=(0, 1, 2))(q, k, v)
    for gf, gd, name in zip(g_flash, g_dense, "qkv"):
        np.testing.assert_allclose(gf, gd, atol=5e-5, rtol=5e-4,
                                   err_msg=f"d{name}")


def test_bf16_forward_close():
    q, k, v = _mk(1, 128, 2, 32, jnp.bfloat16, seed=2)
    got = flash_attention(q, k, v, causal=True).astype(jnp.float32)
    want = dense_attention(q, k, v, causal=True).astype(jnp.float32)
    np.testing.assert_allclose(got, want, atol=2e-2, rtol=2e-2)


def test_cross_attention_noncausal():
    ks = jax.random.split(jax.random.key(3), 3)
    q = jax.random.normal(ks[0], (2, 64, 2, 16))
    k = jax.random.normal(ks[1], (2, 128, 2, 16))
    v = jax.random.normal(ks[2], (2, 128, 2, 16))
    got = flash_attention(q, k, v)
    want = dense_attention(q, k, v)
    np.testing.assert_allclose(got, want, atol=2e-6, rtol=2e-6)


def test_fallback_shapes():
    # sequence not a multiple of 8 -> dense fallback, still exact
    q, k, v = _mk(1, 13, 2, 16, jnp.float32, seed=4)
    got = flash_attention(q, k, v, causal=True)
    want = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(got, want, atol=1e-6)
    assert _pick_block(13) is None
    assert _pick_block(192) == 64
    assert _pick_block(1024) == 1024


def test_with_lse_empty_rows_contract():
    # causal with s_q > s_kv (dense fallback): rows whose key set is
    # empty must carry lse = -inf and zero output for exact blockwise
    # merging (the ring schedule's contract).
    from icikit.ops.flash_attention import flash_attention_with_lse
    ks = jax.random.split(jax.random.key(5), 3)
    q = jax.random.normal(ks[0], (1, 8, 2, 16))
    k = jax.random.normal(ks[1], (1, 4, 2, 16))
    v = jax.random.normal(ks[2], (1, 4, 2, 16))
    out, lse = flash_attention_with_lse(q, k, v, causal=True)
    assert np.all(np.isneginf(np.asarray(lse)[:, :, :4]))  # q_pos < 0
    np.testing.assert_array_equal(np.asarray(out)[:, :4], 0.0)
    assert np.all(np.isfinite(np.asarray(lse)[:, :, 4:]))


@pytest.mark.parametrize("causal", [False, True])
def test_tiled_fused_bwd_square_blocks(causal):
    # bq == bk multi-block: the fused one-pass backward's whole-sequence
    # dq scratch accumulates via dynamic-slice stores and flushes once,
    # during the final K row (icikit/ops/flash_attention.py
    # _bwd_fused_tiled_kernel). Pin its grads against the dense oracle.
    from icikit.ops.flash_attention import flash_attention_with_lse
    q, k, v = _mk(1, 512, 2, 32, jnp.float32, seed=6)

    def loss(q, k, v):
        out, _ = flash_attention_with_lse(q, k, v, causal=causal,
                                          block_q=128, block_k=128)
        return jnp.sum(out * jnp.cos(out))

    def loss_dense(q, k, v):
        out = dense_attention(q, k, v, causal=causal)
        return jnp.sum(out * jnp.cos(out))

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gf, gdd, name in zip(g, gd, "qkv"):
        np.testing.assert_allclose(gf, gdd, atol=5e-5, rtol=5e-4,
                                   err_msg=f"d{name}")


def test_bwd_path_selection():
    # the fused tiled path owns every multi-block shape whose fp32 dq
    # accumulator fits the VMEM budget; beyond it the two-kernel
    # fallback takes over
    from icikit.ops.flash_attention import _DQ_SCRATCH_BYTES_MAX
    assert 16384 * 64 * 4 <= _DQ_SCRATCH_BYTES_MAX      # 16k stays fused
    assert 131072 * 64 * 4 <= _DQ_SCRATCH_BYTES_MAX     # 128k stays fused
    assert 1048576 * 64 * 4 > _DQ_SCRATCH_BYTES_MAX     # 1M falls back


def test_unknown_impl_rejected():
    from icikit.ops.flash_attention import resolve_attention_impl
    with pytest.raises(ValueError, match="unknown attention impl"):
        resolve_attention_impl("fash")


def test_constant_shift_matches_online():
    """The constant-shift forward (rowmax replaced by a fixed base-2
    shift, the r4 long-context fwd optimization) matches the online-
    softmax kernel in outputs, lse, and gradients; pathological
    magnitudes trigger the traced fallback and still match."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from icikit.ops.flash_attention import flash_attention_with_lse

    k1, k2, k3 = jax.random.split(jax.random.key(0), 3)
    b, s, h, d = 1, 2048, 2, 64
    q = jax.random.normal(k1, (b, s, h, d), jnp.bfloat16)
    k = jax.random.normal(k2, (b, s, h, d), jnp.bfloat16)
    v = jax.random.normal(k3, (b, s, h, d), jnp.bfloat16)
    o1, l1 = flash_attention_with_lse(q, k, v, causal=True)
    o2, l2 = flash_attention_with_lse(q, k, v, causal=True,
                                      softmax_shift=16.0)
    np.testing.assert_allclose(np.asarray(o1, np.float32),
                               np.asarray(o2, np.float32), atol=2e-2)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2),
                               rtol=1e-5, atol=1e-5)

    def loss(fn):
        return lambda q: fn(q)[0].astype(jnp.float32).sum()

    g1 = jax.grad(loss(lambda q: flash_attention_with_lse(
        q, k, v, causal=True)))(q)
    g2 = jax.grad(loss(lambda q: flash_attention_with_lse(
        q, k, v, causal=True, softmax_shift=16.0)))(q)
    np.testing.assert_allclose(np.asarray(g1, np.float32),
                               np.asarray(g2, np.float32), atol=3e-2)
    # overflow: scores far past the shift's exp2 range must fall back
    qb = (q.astype(jnp.float32) * 120).astype(jnp.bfloat16)
    kb = (k.astype(jnp.float32) * 120).astype(jnp.bfloat16)
    o3, l3 = flash_attention_with_lse(qb, kb, v, causal=True,
                                      softmax_shift=16.0)
    o4, l4 = flash_attention_with_lse(qb, kb, v, causal=True)
    assert bool(jnp.isfinite(l3).all())
    np.testing.assert_allclose(np.asarray(l3), np.asarray(l4),
                               rtol=1e-4)
    # gradients THROUGH the fallback: the cond lives inside the
    # custom_vjp, so the backward sees the final correct residuals —
    # a fallback outside it poisoned gradients with 0 x NaN
    g3 = jax.grad(loss(lambda q: flash_attention_with_lse(
        q, kb, v, causal=True, softmax_shift=16.0)))(qb)
    g4 = jax.grad(loss(lambda q: flash_attention_with_lse(
        q, kb, v, causal=True)))(qb)
    assert bool(jnp.isfinite(g3.astype(jnp.float32)).all())
    np.testing.assert_allclose(np.asarray(g3, np.float32),
                               np.asarray(g4, np.float32), atol=3e-2)
