"""Pipeline-parallel tests: the microbatched pp schedule must be
numerically identical to the plain single-device model on the same
tokens (loss AND grads — the backward pipeline is the autodiff
transpose of the forward ppermute chain, so this checks both)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from icikit.models.transformer import TransformerConfig, init_params, loss_fn
from icikit.models.transformer.model import make_model_mesh
from icikit.models.transformer.pipeline import (
    init_pp_params,
    make_pp_mesh,
    make_pp_train_step,
    pp_loss_fn,
)

CFG = TransformerConfig(vocab=61, d_model=32, n_heads=4, d_head=8,
                        d_ff=64, n_layers=4, max_seq=16,
                        compute_dtype="float32")


def _microbatches(m=4, b=2, s=16, seed=0):
    rng = np.random.default_rng(seed)
    tok = rng.integers(0, CFG.vocab, (m, b, s)).astype(np.int32)
    tgt = rng.integers(0, CFG.vocab, (m, b, s)).astype(np.int32)
    return tok, tgt


def _place_pp(mesh, tok, tgt):
    sh = NamedSharding(mesh, P(None, "dp"))
    return (jax.device_put(jnp.asarray(tok), sh),
            jax.device_put(jnp.asarray(tgt), sh))


@pytest.mark.parametrize("dp,pp,m", [(1, 4, 4), (2, 2, 4), (1, 2, 6),
                                     (2, 4, 2)])
def test_pp_matches_single_device(dp, pp, m):
    tok, tgt = _microbatches(m=m)
    ppmesh = make_pp_mesh(dp=dp, pp=pp)
    pparams = init_pp_params(jax.random.key(0), CFG, ppmesh)
    loss_pp, g_pp = pp_loss_fn(pparams, *_place_pp(ppmesh, tok, tgt),
                               ppmesh, CFG, n_microbatches=m)

    # reference: the plain model on the microbatches flattened into one
    # batch (same tokens, same params by construction of init_pp_params)
    mesh1 = make_model_mesh(dp=1, tp=1, sp=1)
    params1 = init_params(jax.random.key(0), CFG, mesh1)
    flat_tok = tok.reshape(-1, tok.shape[-1])
    flat_tgt = tgt.reshape(-1, tgt.shape[-1])
    sh = NamedSharding(mesh1, P("dp", "sp"))
    loss1, g1 = loss_fn(params1, jax.device_put(jnp.asarray(flat_tok), sh),
                        jax.device_put(jnp.asarray(flat_tgt), sh),
                        mesh1, CFG)

    np.testing.assert_allclose(float(loss_pp), float(loss1), rtol=1e-5)
    for k in g1:
        np.testing.assert_allclose(np.asarray(g_pp[k]), np.asarray(g1[k]),
                                   rtol=3e-4, atol=3e-5, err_msg=k)


def test_pp_train_step_learns():
    import optax
    mesh = make_pp_mesh(dp=2, pp=4)
    params = init_pp_params(jax.random.key(1), CFG, mesh)
    tok, tgt = _microbatches(m=4, seed=2)
    tok_d, tgt_d = _place_pp(mesh, tok, tgt)
    optimizer, step = make_pp_train_step(mesh, CFG, 4, optax.adam(1e-2))
    st = optimizer.init(params)
    first = None
    for _ in range(30):
        params, st, loss = step(params, st, tok_d, tgt_d)
        if first is None:
            first = float(loss)
    assert float(loss) < first * 0.5, (first, float(loss))


def test_pp_validation():
    mesh = make_pp_mesh(dp=1, pp=4)
    with pytest.raises(ValueError):
        # 4 layers over pp=3 is impossible; mesh of 3 stages with 4
        # microbatches declared but 2 provided is the cheaper check
        pp_loss_fn({}, jnp.zeros((2, 2, 16), jnp.int32),
                   jnp.zeros((2, 2, 16), jnp.int32), mesh, CFG,
                   n_microbatches=4)
    from icikit.models.transformer.pipeline import pp_param_specs
    with pytest.raises(ValueError):
        pp_param_specs(TransformerConfig(n_experts=4))

@pytest.mark.parametrize("dp,pp,m", [(1, 4, 4), (2, 2, 4), (1, 2, 6)])
def test_pp_1f1b_matches_gpipe(dp, pp, m):
    """The hand-rolled 1F1B backward must reproduce GPipe's loss and
    gradients exactly (same arithmetic, different schedule — the
    interleaving and the explicit cross-shard psums are the only
    differences)."""
    tok, tgt = _microbatches(m=m, seed=5)
    mesh = make_pp_mesh(dp=dp, pp=pp)
    params = init_pp_params(jax.random.key(0), CFG, mesh)
    args = _place_pp(mesh, tok, tgt)
    loss_g, g_g = pp_loss_fn(params, *args, mesh, CFG, n_microbatches=m)
    loss_i, g_i = pp_loss_fn(params, *args, mesh, CFG, n_microbatches=m,
                             schedule="1f1b")
    np.testing.assert_allclose(float(loss_i), float(loss_g), rtol=1e-6)
    for k in g_g:
        np.testing.assert_allclose(np.asarray(g_i[k]), np.asarray(g_g[k]),
                                   rtol=1e-4, atol=1e-6, err_msg=k)


def test_pp_1f1b_traced_schedule_shape():
    """Machine-check the 1F1B schedule: exactly 2 ppermutes in the
    whole trace (forward ring hop + reversed cotangent hop, both in
    the one scan body) and scan length T = m + 2p − 2."""
    from icikit.bench.pipeline import analytic_1f1b_counts
    for p, m in [(2, 4), (4, 4), (4, 16)]:
        cfg = TransformerConfig(vocab=61, d_model=32, n_heads=4,
                                d_head=8, d_ff=64, n_layers=p,
                                max_seq=16, compute_dtype="float32")
        rec = analytic_1f1b_counts(cfg, p, m)
        # both hops live inside the schedule scan: total ppermutes in
        # the WHOLE trace is 2, and exactly one scan of length T
        # contains both (a hop unrolled out of the body, or a stray
        # same-length scan, fails one of these)
        assert rec["ppermutes"] == rec["expected_ppermutes"], rec
        sched = [sc for sc in rec["scans"]
                 if sc == (rec["expected_T"], 2)]
        assert len(sched) == 1, rec


def test_pp_1f1b_activation_memory_advantage():
    """The point of 1F1B: O(p) live activations instead of GPipe's
    O(m). Compare the XLA-reported temp allocation of the two
    compiled programs at m >> p — the 1F1B program must need
    substantially less scratch."""
    m, pp = 16, 4
    mesh = make_pp_mesh(dp=2, pp=pp)
    params = init_pp_params(jax.random.key(0), CFG, mesh)
    tok, tgt = _microbatches(m=m, seed=7)
    args = _place_pp(mesh, tok, tgt)

    def temp_bytes(schedule):
        f = jax.jit(lambda p_, a, b: pp_loss_fn(
            p_, a, b, mesh, CFG, n_microbatches=m, schedule=schedule))
        mem = f.lower(params, *args).compile().memory_analysis()
        if mem is None:
            pytest.skip("backend reports no memory analysis")
        return mem.temp_size_in_bytes

    gp, i1 = temp_bytes("gpipe"), temp_bytes("1f1b")
    assert i1 < 0.7 * gp, (gp, i1)


def test_pp_train_step_1f1b_smoke():
    """The train-step API reaches the 1F1B schedule (review finding:
    the kwarg must be forwarded) and a step runs and learns."""
    import optax
    mesh = make_pp_mesh(dp=2, pp=2)
    params = init_pp_params(jax.random.key(1), CFG, mesh)
    tok, tgt = _microbatches(m=4, seed=3)
    tok_d, tgt_d = _place_pp(mesh, tok, tgt)
    optimizer, step = make_pp_train_step(mesh, CFG, 4, optax.adam(1e-2),
                                         schedule="1f1b")
    st = optimizer.init(params)
    params, st, l0 = step(params, st, tok_d, tgt_d)
    for _ in range(9):
        params, st, loss = step(params, st, tok_d, tgt_d)
    assert float(loss) < float(l0)
