"""Checked collectives (``icikit.parallel.integrity``): the checksum
transport, detection precision, quarantine-and-retry recovery, the
chaos site registry, and the train step's verdict absorption.

The drill suites live in tests/test_chaos_sites.py (per-family SDC
drills) and tests/test_fuzz_collectives.py (randomized corpus); this
file unit-tests the machinery those drills stand on.
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from icikit import chaos
from icikit.parallel import integrity, transport
from icikit.parallel.allgather import all_gather_blocks
from icikit.parallel.allreduce import all_reduce
from icikit.utils.mesh import make_mesh, shard_along


# -- segment_checksum: the bit-fold contract -------------------------

# (64-bit lanes need jax_enable_x64, which this suite keeps off; the
# checksum's uint64 high^low fold stays for x64-enabled stacks)
@pytest.mark.parametrize("dtype", ["int32", "float32", "float16",
                                   "bfloat16", "int8", "uint8"])
def test_checksum_changes_under_every_single_bit_flip(dtype):
    """Exactness, exhaustively on a small payload: flipping ANY single
    bit changes the checksum (detection can never miss), and the
    checksum of the unmodified payload is reproducible (a clean run
    can never false-positive)."""
    rng = np.random.default_rng(7)
    raw = rng.integers(0, 256, size=48, dtype=np.uint8).tobytes()
    a = np.frombuffer(raw, dtype=np.dtype(dtype)
                      if dtype != "bfloat16" else np.uint16)
    base = jnp.asarray(a).view(jnp.bfloat16) if dtype == "bfloat16" \
        else jnp.asarray(a)
    cs = jax.jit(transport.segment_checksum)
    ref = np.asarray(cs(base))
    assert np.asarray(cs(base)) == ref  # deterministic
    buf = bytearray(raw)
    seen = set()
    for bitpos in range(len(raw) * 8):
        buf[bitpos // 8] ^= 1 << (bitpos % 8)
        b = np.frombuffer(bytes(buf), dtype=np.dtype(dtype)
                          if dtype != "bfloat16" else np.uint16)
        flipped = (jnp.asarray(b).view(jnp.bfloat16)
                   if dtype == "bfloat16" else jnp.asarray(b))
        got = np.asarray(cs(flipped))
        assert got != ref, f"missed flip at bit {bitpos} ({dtype})"
        seen.add(int(got))
        buf[bitpos // 8] ^= 1 << (bitpos % 8)  # restore


def test_checked_on_single_device_mesh_is_vacuously_ok():
    """p=1: no exchanges, so the verdict is vacuous and the checked
    path still returns the exact payload (shape contract intact)."""
    x = jnp.asarray(np.random.default_rng(0).standard_normal(64),
                    jnp.float32)
    mesh = make_mesh(1)
    base = np.asarray(all_gather_blocks(x[None], mesh, algorithm="ring",
                                        checked=True))
    again = np.asarray(all_gather_blocks(x[None], mesh, algorithm="ring"))
    np.testing.assert_array_equal(base[0], np.asarray(x)[None])
    np.testing.assert_array_equal(base, again)


# -- checked dispatch: detection, quarantine, retry, exhaustion ------

def test_checked_rejects_vendor_variant(mesh4):
    x = shard_along(jnp.ones((4, 8), jnp.int32), mesh4, "p")
    with pytest.raises(ValueError, match="vendor"):
        all_reduce(x, mesh4, algorithm="xla", checked=True)


def test_detection_names_the_producing_device_and_step(mesh4):
    data = np.arange(4 * 16, dtype=np.int32).reshape(4, 16)
    x = shard_along(jnp.asarray(data), mesh4, "p")
    base = np.asarray(all_gather_blocks(x, mesh4, algorithm="ring"))
    integrity.reset_stats()
    plan = chaos.FaultPlan(seed=5,
                           schedule={"corrupt:collective.allgather": (0,)})
    with chaos.inject(plan):
        healed = np.asarray(all_gather_blocks(x, mesh4, algorithm="ring",
                                              checked=True))
    np.testing.assert_array_equal(healed, base)
    st = integrity.stats()
    assert st["detected"] == 1 and st["retries"] == 1
    assert st["recoveries"] == 1
    # the verdict matrix pinpoints exactly the injected (device, step):
    # corruption at receive step t is caught at step t, not later (the
    # corrupted block's onward journey re-checksums consistently)
    assert len(st["last"]["devices"]) == 1
    assert len(st["last"]["steps"]) == 1
    assert 0 <= st["last"]["steps"][0] < 3  # ring over p=4: 3 steps
    # quarantine ledger mirrors the obs counters
    assert integrity.quarantine_counts() == {st["last"]["devices"][0]: 1}


def test_persistent_corruption_exhausts_retries(mesh4):
    x = shard_along(jnp.asarray(
        np.arange(4 * 8, dtype=np.int32).reshape(4, 8)), mesh4, "p")
    integrity.reset_stats()
    # rate 1.0: every attempt's dispatch decision fires — a stuck-at
    # fault, not a transient
    plan = chaos.FaultPlan(rates={"corrupt:collective.allgather": 1.0})
    with chaos.inject(plan):
        with pytest.raises(integrity.IntegrityError, match="persistent"):
            all_gather_blocks(x, mesh4, algorithm="ring", checked=True,
                              retries=2)
    assert plan.fired("corrupt", "collective.allgather") == 3
    assert integrity.stats()["detected"] == 3


def test_retry_consumes_plan_indices_deterministically(mesh4):
    """Two identical drills replay identically: same fired log, same
    recovered bytes — the whole recovery is a pure function of the
    plan (the chaos module's core contract, extended in-schedule)."""
    x = shard_along(jnp.asarray(
        np.arange(4 * 8, dtype=np.int32).reshape(4, 8)), mesh4, "p")

    def drill():
        integrity.reset_stats()
        plan = chaos.FaultPlan(
            seed=3, schedule={"corrupt:collective.allreduce": (0, 1)})
        with chaos.inject(plan):
            out = np.asarray(all_reduce(x, mesh4, algorithm="ring",
                                        checked=True))
        return out, sorted(plan.log), integrity.stats()["detected"]

    out1, log1, d1 = drill()
    out2, log2, d2 = drill()
    np.testing.assert_array_equal(out1, out2)
    assert log1 == log2 and d1 == d2 == 2
    np.testing.assert_array_equal(
        out1, np.asarray(all_reduce(x, mesh4, algorithm="ring")))


# -- site registry ---------------------------------------------------

def test_registered_sites_cover_the_checked_families():
    for fam in integrity.CHECKED_FAMILIES:
        assert chaos.site_known(f"collective.{fam}")
    assert chaos.site_known("collective.*")


def test_inject_warns_on_unknown_site_glob():
    assert chaos.registered_sites()  # instrumented modules imported
    plan = chaos.FaultPlan(
        rates={"die:collective.allgatherr": 0.5})  # chaos-site-lint: off
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        with chaos.inject(plan):
            pass
    assert any("no registered probe site" in str(x.message) for x in w)


def test_inject_stays_quiet_for_known_sites_and_patterns():
    import icikit.models.solitaire.scheduler  # noqa: F401 (registers)

    plan = chaos.FaultPlan(rates={"die:solitaire.worker.*": 0.5,
                                  "corrupt:collective.allgather": 0.1,
                                  "die:solitaire.worker.1": 0.1})
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        with chaos.inject(plan):
            pass
    assert not [x for x in w
                if "no registered probe site" in str(x.message)]


# -- train step absorbs the checked grad-sync verdict ----------------

def _tiny_setup(grad_check):
    from jax.sharding import NamedSharding, PartitionSpec as P

    from icikit.models.transformer import (
        TransformerConfig, init_params, make_train_step)
    from icikit.models.transformer.model import make_model_mesh

    cfg = TransformerConfig(vocab=32, d_model=32, n_heads=2, d_head=8,
                            d_ff=64, n_layers=1, max_seq=16,
                            compute_dtype="float32")
    mesh = make_model_mesh(dp=2, tp=1, sp=1)
    params = init_params(jax.random.key(0), cfg, mesh)
    opt, step = make_train_step(mesh, cfg, guard="device",
                                grad_check=grad_check)
    state = opt.init(params)
    rng = np.random.default_rng(0)
    sh = NamedSharding(mesh, P("dp", "sp"))
    tok = jax.device_put(jnp.asarray(rng.integers(0, 32, (4, 16))), sh)
    tgt = jax.device_put(jnp.asarray(rng.integers(0, 32, (4, 16))), sh)
    return params, state, step, tok, tgt


def test_grad_check_requires_device_guard():
    from icikit.models.transformer import TransformerConfig, make_train_step
    from icikit.models.transformer.model import make_model_mesh

    cfg = TransformerConfig(vocab=32, d_model=32, n_heads=2, d_head=8,
                            d_ff=64, n_layers=1, max_seq=16)
    mesh = make_model_mesh(dp=2, tp=1, sp=1)
    with pytest.raises(ValueError, match="guard='device'"):
        make_train_step(mesh, cfg, guard="none", grad_check="ring")


def test_corrupted_grad_sync_skips_the_commit():
    from icikit.models.transformer.model import GRAD_SYNC_SITE

    params, state, step, tok, tgt = _tiny_setup("ring")
    taint_off = jnp.asarray(chaos.TAINT_OFF)
    p_ok, st_ok, loss, ok = step(params, state, tok, tgt, taint_off)
    assert bool(np.asarray(ok))
    assert any(not np.array_equal(np.asarray(a), np.asarray(b))
               for a, b in zip(jax.tree.leaves(params),
                               jax.tree.leaves(p_ok)))

    plan = chaos.FaultPlan(
        seed=2, schedule={f"corrupt:{GRAD_SYNC_SITE}": (0,)})
    with chaos.inject(plan):
        taint = jnp.asarray(
            chaos.traced_corrupt_spec(GRAD_SYNC_SITE, 1, 2))
    assert plan.fired("corrupt", GRAD_SYNC_SITE) == 1
    p_bad, st_bad, loss_bad, ok_bad = step(params, state, tok, tgt,
                                           taint)
    assert not bool(np.asarray(ok_bad))
    # the where(ok, new, old) select held EVERYTHING: params and
    # optimizer state are bitwise untouched by the corrupted step
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(p_bad)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(st_bad)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_checked_step_bitwise_matches_unchecked_on_clean_runs():
    params, state, step, tok, tgt = _tiny_setup("ring")
    params2, state2, plain, _, _ = _tiny_setup("none")
    out = step(params, state, tok, tgt, jnp.asarray(chaos.TAINT_OFF))
    ref = plain(params2, state2, tok, tgt)
    np.testing.assert_array_equal(np.asarray(out[2]), np.asarray(ref[2]))
    for a, b in zip(jax.tree.leaves(out[0]), jax.tree.leaves(ref[0])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
