"""Pipeline bubble study: analytic schedule structure + measured path."""

import jax
import pytest

from icikit.bench.pipeline import (analytic_pp_counts, bubble_sweep,
                                   fit_and_render)
from icikit.models.transformer import TransformerConfig


def _tiny(pp):
    return TransformerConfig(vocab=64, d_model=32, n_heads=2, d_head=16,
                             d_ff=64, n_layers=pp, max_seq=16,
                             compute_dtype="float32")


@pytest.mark.parametrize("p,m", [(2, 1), (2, 4), (4, 1), (4, 8)])
def test_analytic_ppermute_count(p, m):
    """The traced fwd+bwd program holds exactly 2(m+p-2) ppermutes —
    the forward chain plus its autodiff transpose (the backward
    pipeline), machine-checking the schedule length and the transpose
    property the pipeline module claims."""
    r = analytic_pp_counts(_tiny(p), p, m)
    assert r["ppermutes"] == r["expected_ppermutes"] == 2 * (m + p - 2)
    assert r["sweeps"] == m + p - 1


def test_bubble_sweep_efficiency_improves_with_m():
    """More microbatches amortize the bubble: per-token time must be
    cheaper at m=4 than m=1 (ideal: 2.29x; any measured improvement
    >1.3x passes — the CPU fabric is noisy)."""
    if len(jax.devices()) < 4:
        pytest.skip("needs a 4-device mesh")
    recs = bubble_sweep(pp=4, ms=(1, 4), b_micro=1, s=32, runs=2)
    by_m = {r["m"]: r["per_token_us"] for r in recs}
    assert by_m[1] / by_m[4] > 1.3
    text = fit_and_render([], recs)
    assert "Measured per-token time" in text


def test_render_marks_mismatch():
    r = analytic_pp_counts(_tiny(2), 2, 2)
    r_bad = dict(r, ppermutes=r["ppermutes"] + 1)
    assert "MISMATCH" in fit_and_render([r_bad], [])
    assert "MISMATCH" not in fit_and_render([r], [])
