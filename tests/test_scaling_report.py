"""Tests for the strong-scaling launcher (C27 analog) and the markdown
report renderer (C29 analog)."""

from __future__ import annotations

import json

import pytest

from icikit.bench.report import render_report
from icikit.bench.scaling import run_scaling_sweep


def _rec(family="allgather", algorithm="ring", p=2, msize=16,
         best_s=1e-5, verified=True):
    return {"family": family, "algorithm": algorithm, "p": p,
            "msize": msize, "dtype": "int32", "bytes_per_block": msize * 4,
            "runs": 3, "mean_s": best_s * 1.1, "best_s": best_s,
            "busbw_gbps": 1.0, "verified": verified}


def test_report_tables_and_ranking():
    records = []
    for p in (2, 4):
        for m in (16, 256):
            records.append(_rec(algorithm="ring", p=p, msize=m,
                                best_s=1e-5))
            records.append(_rec(algorithm="xla", p=p, msize=m,
                                best_s=2e-5))
    text = render_report(records, title="T")
    assert "# T" in text
    assert "best time (µs) vs message size, p=2" in text
    assert "vs device count, msize=16" in text  # p varies -> scaling view
    assert "**ring** fastest in 4/4 configurations" in text
    assert "faster (median)" in text


def test_report_marks_unverified():
    text = render_report([_rec(verified=False)])
    assert "unverified" in text
    assert "✗" in text


def test_report_single_p_skips_scaling_view():
    text = render_report([_rec(p=4)])
    assert "vs device count" not in text


@pytest.mark.slow
def test_scaling_sweep_subprocess_smoke():
    """One real scale point through the subprocess path: p=2 simulated
    CPU mesh, tiny sizes. This is the sub.sh analog end-to-end."""
    records = run_scaling_sweep(
        "allgather", ps=(2,), algorithms=["ring"], sizes=(4,), runs=1,
        timeout_s=300.0)
    assert len(records) == 1
    r = records[0]
    assert r["p"] == 2 and r["algorithm"] == "ring" and r["verified"]
    # records are json-serializable end-to-end
    json.dumps(records)


@pytest.mark.slow
def test_sort_scaling_subprocess_smoke():
    """The sorting study through the strong-scaling launcher — the
    reference's project3.pdf scaling figure, one scale point."""
    from icikit.bench.scaling import _render_sort_scaling
    records = run_scaling_sweep(
        None, ps=(2,), algorithms=["sample"], sizes=(2048,), runs=1,
        timeout_s=300.0, bench="sort")
    assert len(records) == 1
    r = records[0]
    assert r["p"] == 2 and r["algorithm"] == "sample" and r["errors"] == 0
    text = _render_sort_scaling(records)
    assert "Mkeys/s vs p" in text and "sample" in text
