"""The r18 kill-the-leader soak as a test: HA under compound chaos.

Five PROCESSES of control plane (1 leader + 2 warm standbys) and an
elastic engine roster serve a greedy trace while the leader dies
mid-journal-append (torn tail), its successor is SIGKILLed
mid-decode, the promotions ride the epoch-collision and rotten-lease
drills, one engine is chaos-killed, and a joiner is alert-spawned.
Exit bar, enforced inside ``tools/fleet_ha_study.soak``: every
request completes bitwise vs single-request decode, zero duplicate
commits, every driver-measured failover under 2x the lease timeout,
and every drill observed in the record.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools"))


@pytest.mark.slow
@pytest.mark.chaos
def test_kill_the_leader_soak(tmp_path):
    from fleet_ha_study import soak

    rec = soak(json_path=str(tmp_path / "soak.jsonl"),
               n_requests=32, lease_timeout_s=1.5, timeout_s=600.0)
    # the soak asserts its own bars; re-state the headline ones here
    assert rec["completed"] == 32 and not rec["failed"]
    assert rec["identity_ok"]
    assert rec["duplicate_commits"] == 0
    assert rec["coordinators"]["coord0"]["returncode"] == 23
    assert rec["leader_kills"] >= 1
    assert all(ms < 3000.0 for ms in rec["failover_ms"])
    assert rec["chaos_events"]["epoch_collision"] >= 1
    assert rec["scaleup_ttft_ms"] is not None
