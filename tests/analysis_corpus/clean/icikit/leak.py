"""obs-print clean twin: machine-readable output that is not bare
print telemetry — the pinned grep semantics match only a print of a
json dump, so a stream write stays clean (exactly like the grep
ancestor)."""
import json
import sys

sys.stdout.write(json.dumps({"event": "ok"}) + "\n")
