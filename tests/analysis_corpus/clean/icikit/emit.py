"""obs-catalog clean twin: the emitted name IS catalogued."""
from icikit import obs

obs.count("serve.bogus_counter")
