"""fleet-control-plane clean twin (r19): host-only telemetry — batch
payloads are bytes + hashlib digests, queues are host structures."""
import hashlib

DIGEST = hashlib.blake2b(b"batch", digest_size=16).hexdigest()
