"""fleet-control-plane clean twin: host-only control plane — leases
and claims live in host structures, KV bytes move as numpy views."""
import numpy as np

LEASE_TABLE = np.zeros((8,))
