"""serve-clock clean twin: SLO math on the monotonic clock."""
import time

t0 = time.monotonic()
