"""host-sync clean twin: ONE batched materialization at the fence
(the step function is a documented fence), then host-side loops.

(References _accept_window and _accept_tree so the tree-accept rule's
engine-imports-the-shared-rule check stays out of this twin's frame.)
"""
import numpy as np


class Engine:
    def _step(self):
        outs = self._step_fns[0](self.params)
        g, a = outs
        a = np.asarray(a)       # the fence's one batched drain
        x = 0
        for slot in range(4):
            x += float(a[slot])
        return x
