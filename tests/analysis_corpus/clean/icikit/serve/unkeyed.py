"""serve-key clean twin: randomness rides the per-request counter
stream, threaded in as data (no key construction here)."""


def next_token(stream_data, pos):
    return stream_data[pos]
