"""fleet-control-plane clean twin (r19): host-only aggregation —
rollups are plain floats in a host registry."""

ROLLUP = sum([0.0, 1.0]) / 2.0
