"""tree-accept clean twin: the tree accept RUNS the chain accept."""


def _accept_window(draft, target):
    return draft == target


def _accept_tree(draft, target):
    return _accept_window(draft, target)
