"""chaos-site clean twin: the plan entry names a registered site."""

PLAN = {"corrupt:serve.kv.page": "@0"}
