"""chaos-site seeded violation: a plan entry naming no registered
probe site."""

PLAN = {"die:definitely.not.a.site": "@0"}
