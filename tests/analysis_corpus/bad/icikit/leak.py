"""obs-print seeded violation: bare JSON telemetry print."""
import json

print(json.dumps({"event": "leak"}))
