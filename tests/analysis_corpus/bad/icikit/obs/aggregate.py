"""fleet-control-plane seeded violation (r19): a jax dispatch inside
the collector — aggregation runs in the coordinator process, whose
claim path must never stall behind an XLA dispatch."""

ROLLUP = jax.numpy.zeros((4,))  # noqa: F821 - corpus fixture
