"""host-sync seeded violation: a per-item sync inside the step loop.

(References _accept_window and _accept_tree so the tree-accept rule's
engine-imports-the-shared-rule check stays out of this twin's frame.)
"""


class Engine:
    def _step(self):
        outs = self._step_fns[0](self.params)
        g, a = outs
        x = 0
        for slot in range(4):
            x += float(a[slot])
        return x
