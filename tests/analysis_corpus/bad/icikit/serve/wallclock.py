"""serve-clock seeded violation: wall clock in the serving path."""
import time

t0 = time.time()
