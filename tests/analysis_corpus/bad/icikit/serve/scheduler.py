"""journal-discipline seeded violation: a RequestQueue verb mutates
the lease table without journaling — replay would never see it."""
import threading


class RequestQueue:
    def __init__(self):
        self._lock = threading.Lock()
        self._leases = {}
        self.log = []

    def _journal(self, verb, rec):
        self.log.append((verb, rec))

    def claim(self, rid, seq):
        with self._lock:
            self._leases[rid] = (0.0, seq)
            self._journal("claim", {"rid": rid, "seq": seq})

    def promote(self, rid, seq):
        with self._lock:
            self._leases[rid] = (-1.0, seq)
