"""serve-key seeded violation: an unkeyed host RNG draw."""
import numpy as np

tok = np.random.randint(0, 7)
