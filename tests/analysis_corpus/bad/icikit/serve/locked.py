"""lock-discipline seeded violation: bus emission under the lock."""
import threading

from icikit import obs


class Leases:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0

    def bump(self):
        with self._lock:
            self.n += 1
            obs.count("serve.submitted")
