"""fleet-control-plane seeded violation: a jnp allocation on the
claim path (the import is elsewhere; the allocation is the sin)."""

LEASE_TABLE = jnp.zeros((8,))  # noqa: F821 - corpus fixture
