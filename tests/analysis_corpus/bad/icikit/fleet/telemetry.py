"""fleet-control-plane seeded violation (r19): a jax import in the
telemetry forwarder — the channel must keep flowing while device
schedules are suspect, so jax has no business here."""

import jax  # noqa: F401 - corpus fixture
