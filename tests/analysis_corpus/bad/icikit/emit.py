"""obs-catalog seeded violation: an uncatalogued telemetry name."""
from icikit import obs

obs.count("serve.bogus_counter")
