"""tree-accept seeded violation: _accept_tree forks the chain rule
instead of calling _accept_window."""


def _accept_window(draft, target):
    return draft == target


def _accept_tree(draft, target):
    return draft == target      # re-implements the accept: banned
