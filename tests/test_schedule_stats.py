"""Analytic schedule statistics (``icikit.bench.schedule_stats``):
the traced round/byte counts must reproduce the textbook forms the
reference report derives analytically (report.pdf §§2.2-2.4) — this is
the machine-independent validation of the cost models, decoupled from
the fabric the timings run on."""

from __future__ import annotations

import pytest

from icikit.bench.schedule_stats import analyze_collective, render_markdown


def test_allgather_forms():
    m, b = 4096, 4096 * 4
    for p in (4, 8, 16):
        ring = analyze_collective("allgather", "ring", p, m)
        assert ring.rounds == p - 1 and ring.calls == p - 1
        assert ring.bytes_per_dev == (p - 1) * b
        rd = analyze_collective("allgather", "recursive_doubling", p, m)
        assert rd.rounds == p.bit_length() - 1          # ceil(log2 p)
        assert rd.bytes_per_dev == (p - 1) * b          # same volume
        nv = analyze_collective("allgather", "naive", p, m)
        # p-1 independent rotations: depth 1, a serializing fabric
        # pays the call count
        assert nv.rounds == 1 and nv.calls == p - 1


def test_alltoall_hypercube_volume():
    m, b = 1024, 1024 * 4
    st = analyze_collective("alltoall", "hypercube", 8, m)
    # log p rounds, each moving half the p-block buffer
    assert st.rounds == 3
    assert st.bytes_per_dev == 3 * (8 * b // 2)
    ec = analyze_collective("alltoall", "ecube", 8, m)
    assert ec.rounds == 1 and ec.calls == 7
    assert ec.bytes_per_dev == 7 * b


def test_allreduce_forms():
    m, b = 4096, 4096 * 4
    ring = analyze_collective("allreduce", "ring", 8, m)
    # reduce-scatter (p-1 chunk steps) + allgather (p-1): 2(p-1) deep
    assert ring.rounds == 2 * 7
    rd = analyze_collective("allreduce", "recursive_doubling", 8, m)
    assert rd.rounds == 3
    assert rd.bytes_per_dev == 3 * b   # full vector every round


def test_vendor_flagged():
    st = analyze_collective("allgather", "xla", 8, 1024)
    assert st.vendor_calls == 1 and st.rounds == 1


def test_render_and_update(tmp_path):
    md = render_markdown(ps=(4, 8), msize=256,
                         families=("allgather", "scan"))
    assert "### allgather" in md and "### scan" in md
    # pow2 ps: every allgather variant must analyze (no n/a cells)
    assert "n/a" not in md.split("### allgather")[1].split("###")[0]
    out = tmp_path / "S.md"
    out.write_text("# header\n\nbody\n")
    from icikit.bench import schedule_stats
    old = schedule_stats.render_markdown
    schedule_stats.render_markdown = lambda: md
    try:
        schedule_stats.update_scaling_md(str(out))
        schedule_stats.update_scaling_md(str(out))  # idempotent refresh
    finally:
        schedule_stats.render_markdown = old
    text = out.read_text()
    assert text.count("## Analytic round/byte counts") == 1
    assert text.startswith("# header")


def test_nonpow2_marked_na():
    md = render_markdown(ps=(6,), msize=64, families=("allgather",))
    row = [ln for ln in md.splitlines()
           if ln.startswith("| recursive_doubling |")][0]
    assert "n/a" in row


def test_sort_schedule_forms():
    """The traced sort schedules reproduce their textbook forms:
    bitonic has d(d+1)/2 full-block rounds; sample sort's depth is
    p-independent; the hybrid adds the splitter bitonic's depth;
    quicksort's calls grow linearly in d (pivot + exchange stages)."""
    from icikit.bench.schedule_stats import analyze_sort

    n = 1 << 14
    for p in (2, 4, 8):
        d = p.bit_length() - 1
        bi = analyze_sort("bitonic", p, n)
        assert bi.rounds == d * (d + 1) // 2
        assert bi.calls == bi.rounds  # full-block ppermute per round
        # full block crosses each round: bytes = rounds * n/p * 4
        assert bi.bytes_per_dev == bi.rounds * (n // p) * 4
    depths = [analyze_sort("sample", p, n).rounds for p in (2, 4, 8)]
    assert len(set(depths)) == 1  # constant communication depth
    for p in (4, 8):
        d = p.bit_length() - 1
        hy = analyze_sort("sample_bitonic", p, n)
        assert hy.rounds == depths[0] + d * (d + 1) // 2
        qs = analyze_sort("quicksort", p, n)
        assert qs.rounds >= 2 * d  # >= pivot + exchange per round


def test_sort_render_markdown():
    from icikit.bench.schedule_stats import render_sort_markdown

    text = render_sort_markdown(ps=(2, 4), n=1 << 12)
    assert "| bitonic |" in text and "| quicksort |" in text
    assert "rounds/calls/MB-dev" in text


def test_crossover_prediction_structure():
    """The crossover predictor (r5): structure + the two model
    properties that carry the science — bitonic wins the small-p
    low-latency regime, and raising per-round latency can only move
    the crossover EARLIER (the latency-depth mechanism)."""
    from icikit.bench.crossover import (alpha_key, crossover_table,
                                        render_markdown)

    tab = crossover_table(1 << 16, ps=(2, 4, 8, 16, 32, 64),
                          alphas_us=(1.0, 50.0))
    assert tab["algs"] == ["bitonic", "quicksort"]
    t1 = tab["times"][alpha_key(1.0)]
    assert t1["bitonic"][0] < t1["quicksort"][0]  # small p: bitonic
    crossings = [tab["crossover_p"][alpha_key(a)] for a in (1.0, 50.0)]
    # higher alpha crosses no later than lower alpha (None = never)
    if crossings[0] is not None:
        assert crossings[1] is not None
        assert crossings[1] <= crossings[0]
    md = render_markdown(tab)
    assert "crossover" in md and "| 50 |" in md


def test_crossover_table_json_roundtrip():
    """The per-α maps are keyed by strings (alpha_key), so the
    in-memory table and its crossover.jsonl serialization have the
    SAME shape — json.dumps silently stringified the old float keys,
    making every consumer of the file diverge from every consumer of
    the dict. Traces are seeded synthetically so this pin is a pure
    shape test (analyze_sort itself is exercised above and its
    AbstractMesh path is a known jax-0.4.37 env gap)."""
    import json

    from icikit.bench import crossover

    n, ps = 1 << 14, (2, 4, 8)
    seeded = {}
    for alg in ("bitonic", "quicksort"):
        for p in ps:
            key = (alg, p, n)
            seeded[key] = crossover._TRACE_CACHE.get(
                key, (p.bit_length(), 4 * n // p))
    old = dict(crossover._TRACE_CACHE)
    crossover._TRACE_CACHE.update(seeded)
    try:
        tab = crossover.crossover_table(n, ps=ps, alphas_us=(1.0, 25.0))
    finally:
        crossover._TRACE_CACHE.clear()
        crossover._TRACE_CACHE.update(old)
    back = json.loads(json.dumps(tab))
    assert back == tab
    assert set(tab["times"]) == {"1", "25"}
    assert set(tab["crossover_p"]) == {"1", "25"}
