"""Token-tree speculation (round 14) — correctness, exactness, and
chaos coverage.

The load-bearing claims, each pinned here:

- **b=1 IS the chain path** — ``tree_branch=1`` routes to the same
  builder key and the same compiled program as the pre-tree call
  (bitwise, trivially: there is one accept implementation, and the
  caterpillar degenerates to the chain at b=1 by construction — the
  ``make check`` lint enforces that structurally).
- **temp→0 collapses bitwise to greedy longest-prefix accept** —
  tree-speculated greedy (and temperature-0 sampled) output equals
  ``greedy_generate`` bitwise across dp/tp meshes, drafters, and
  branch counts (the full mesh × drafter × b cross product runs
  under the slow marker; tier-1 keeps a spanning subset).
- **sampled acceptance is distribution-exact** — tree-speculated
  sampled output is bitwise ``sample_generate`` at matched seeds
  (every committed token is the model's own keyed draw at its
  position — the sideways hop merely finds that draw on a different
  pre-verified node), and a two-sample chi-square over DISJOINT seed
  sets at matched (T, top_p) pins the distribution claim
  statistically, not just by key bookkeeping.
- **the sideways hop is live machinery** — a branch count covering
  the whole vocab forces every primary miss onto a sibling, so
  ``sideways_accepted`` > 0 and per-pass accepted length strictly
  improves over the chain (the tree must not be dead code that
  passes identity tests vacuously).
- **engine ≡ single-request generate with trees on** — the serving
  engine's tree verify windows commit bitwise what single-request
  ``greedy_generate`` / ``sample_generate`` commit, per request,
  across drafters, branch counts, kv arenas, and staggered mixed
  traffic.
- **chaos sites** — ``decode.spec.tree.build`` (die/delay at the
  ranked-proposal program dispatch), ``decode.spec.tree.verify``
  (SDC on the stats readback skews counters only, never tokens),
  ``serve.spec.tree.fork`` (die/delay at the engine's tree-window
  CoW-guard boundary: leases expire, a second engine completes
  token-identically); clean armed runs stay bit-identical.

Shapes are deliberately uniform across tests (b=2 rows, 8-token
prompts, n_new=10, k=3): ``_build_speculative`` / the decode
builders cache per (mesh, cfg, shape, …) and jax Meshes compare by
value, so uniform shapes let the tests share compiled programs —
the suite must fit the tier-1 wall-clock budget.
"""

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from icikit import chaos
from icikit.models.transformer import (
    TransformerConfig,
    init_params,
    speculative_generate,
)
from icikit.models.transformer.decode import (
    greedy_generate,
    sample_generate,
)
from icikit.models.transformer.model import make_model_mesh
from icikit.models.transformer.speculative import (
    speculative_sample_generate,
)
from icikit.serve import Engine, RequestQueue, ServeConfig

CFG = TransformerConfig(vocab=61, d_model=32, n_heads=4, d_head=8,
                        d_ff=64, n_layers=2, max_seq=64,
                        compute_dtype="float32")
N_NEW = 10


def _put(mesh, arr):
    return jax.device_put(jnp.asarray(arr),
                          NamedSharding(mesh, P("dp", None)))


def _prompts(b, s, seed=0, vocab=61):
    rng = np.random.default_rng(seed)
    return rng.integers(0, vocab, (b, s)).astype(np.int32)


def _setup(dp=1, tp=1, b=2, s=8, seed=0, cfg=CFG):
    mesh = make_model_mesh(dp=dp, tp=tp, sp=1)
    params = init_params(jax.random.key(0), cfg, mesh)
    return mesh, params, _put(mesh, _prompts(b, s, seed=seed,
                                             vocab=cfg.vocab))


# -- b=1 is the chain path -------------------------------------------

def test_tree_b1_bitwise_chain_greedy_and_stats():
    mesh, params, pd = _setup()
    chain, st_c = speculative_generate(params, pd, mesh, CFG, N_NEW,
                                       k=3, return_stats=True)
    tree, st_t = speculative_generate(params, pd, mesh, CFG, N_NEW,
                                      k=3, tree_branch=1,
                                      return_stats=True)
    np.testing.assert_array_equal(np.asarray(tree), np.asarray(chain))
    # same program ⇒ same iteration trace, not just same tokens
    assert st_t["verify_steps"] == st_c["verify_steps"]
    assert st_t["draft_accepted"] == st_c["draft_accepted"]
    # chain-path invariants of the widened stats vector: every
    # accepted token is a primary match, no iteration ends sideways
    assert st_t["primary_accepted"] == st_t["draft_accepted"]
    assert st_t["sideways_accepted"] == 0


def test_tree_b1_bitwise_chain_sampled():
    mesh, params, pd = _setup()
    key = jax.random.key(2)
    chain = np.asarray(speculative_sample_generate(
        params, pd, mesh, CFG, N_NEW, key, k=3, temperature=0.9,
        top_p=0.95, seeds=[1, 2]))
    tree = np.asarray(speculative_sample_generate(
        params, pd, mesh, CFG, N_NEW, key, k=3, temperature=0.9,
        top_p=0.95, seeds=[1, 2], tree_branch=1))
    np.testing.assert_array_equal(tree, chain)


# -- temp→0 collapses bitwise to greedy ------------------------------

def test_tree_greedy_collapse():
    """Tree-speculated greedy == greedy_generate bitwise: the ngram
    drafter over b ∈ {1, 2, 4} plus the shared drafter's widest tree
    (the full drafter × b grid runs under the slow marker); one
    baseline."""
    mesh, params, pd = _setup()
    base = np.asarray(greedy_generate(params, pd, mesh, CFG, N_NEW))
    for drafter, nb in (("ngram", 1), ("ngram", 2), ("ngram", 4),
                        ("shared", 4)):
        got = np.asarray(speculative_generate(
            params, pd, mesh, CFG, N_NEW, k=3, drafter=drafter,
            tree_branch=nb))
        np.testing.assert_array_equal(got, base, err_msg=str(
            (drafter, nb)))


@pytest.mark.parametrize("dp,tp", [(2, 2)])
def test_tree_greedy_collapse_sharded(dp, tp):
    """Sharded spanning subset — the dp×tp mesh exercises both
    parallel axes (the full mesh × drafter × b product, incl. the
    dp-only mesh, runs under the slow marker below)."""
    mesh, params, pd = _setup(dp=dp, tp=tp)
    base = np.asarray(greedy_generate(params, pd, mesh, CFG, N_NEW))
    for drafter, nb in (("ngram", 2), ("shared", 4)):
        got = np.asarray(speculative_generate(
            params, pd, mesh, CFG, N_NEW, k=3, drafter=drafter,
            tree_branch=nb))
        np.testing.assert_array_equal(got, base, err_msg=str(
            (drafter, nb)))


@pytest.mark.slow
def test_tree_greedy_collapse_exhaustive():
    """The full dp/tp × drafter × b∈{1,2,4} cross product (the
    acceptance-criteria grid, complete)."""
    for dp, tp in ((1, 1), (2, 1), (2, 2)):
        mesh, params, pd = _setup(dp=dp, tp=tp)
        base = np.asarray(greedy_generate(params, pd, mesh, CFG,
                                          N_NEW))
        for drafter in ("ngram", "shared"):
            for nb in (1, 2, 4):
                got = np.asarray(speculative_generate(
                    params, pd, mesh, CFG, N_NEW, k=3,
                    drafter=drafter, tree_branch=nb))
                np.testing.assert_array_equal(
                    got, base, err_msg=str((dp, tp, drafter, nb)))


def test_tree_temp_zero_is_greedy_accept_bitwise():
    """temperature → 0 pins the sampled tree route onto the greedy
    longest-prefix accept: spec-sampled(T=0, tree) == greedy
    generate, bitwise."""
    mesh, params, pd = _setup()
    greedy = np.asarray(greedy_generate(params, pd, mesh, CFG, N_NEW))
    spec_t0 = np.asarray(speculative_sample_generate(
        params, pd, mesh, CFG, N_NEW, jax.random.key(6), k=3,
        temperature=0.0, drafter="ngram", tree_branch=3))
    np.testing.assert_array_equal(spec_t0, greedy)


def test_tree_trained_drafter_identity():
    """The trained head's top-b logits rank the siblings — identity
    must hold regardless of head quality (proposals price throughput,
    never tokens)."""
    cfg = dataclasses.replace(CFG, n_layers=4, draft_head=True,
                              draft_layers=1, draft_rank=4)
    mesh, params, pd = _setup(cfg=cfg)
    base = np.asarray(greedy_generate(params, pd, mesh, cfg, N_NEW))
    got = np.asarray(speculative_generate(
        params, pd, mesh, cfg, N_NEW, k=3, drafter="trained",
        tree_branch=2))
    np.testing.assert_array_equal(got, base)


# -- sampled exactness -----------------------------------------------

def test_tree_sampled_bitwise_vs_sample_generate():
    """Multi-branch rejection sampling commits the identical sequence
    the sequential sampled loop draws: the verify draw either lands
    on a ranked one-hot proposal (accepting that branch) or IS the
    normalized-residual resample — either way it is the sequential
    loop's keyed draw, bitwise. One baseline, both drafters × b."""
    mesh, params, pd = _setup()
    key = jax.random.key(2)
    base = np.asarray(sample_generate(
        params, pd, mesh, CFG, N_NEW, key, temperature=0.9,
        top_p=0.95, seeds=[1, 2]))
    for drafter, nb in (("ngram", 2), ("shared", 4)):
        got = np.asarray(speculative_sample_generate(
            params, pd, mesh, CFG, N_NEW, key, k=3,
            temperature=0.9, top_p=0.95, seeds=[1, 2],
            drafter=drafter, tree_branch=nb))
        np.testing.assert_array_equal(got, base, err_msg=str(
            (drafter, nb)))


def test_tree_sampled_identity_sharded():
    mesh, params, pd = _setup(dp=2, tp=2)
    key = jax.random.key(3)
    base = np.asarray(sample_generate(
        params, pd, mesh, CFG, N_NEW, key, temperature=1.2, top_k=16))
    got = np.asarray(speculative_sample_generate(
        params, pd, mesh, CFG, N_NEW, key, k=3, temperature=1.2,
        top_k=16, drafter="ngram", tree_branch=2))
    np.testing.assert_array_equal(got, base)


@pytest.mark.slow
def test_tree_sampled_identity_sharded_exhaustive():
    for dp, tp in ((2, 1), (2, 2)):
        mesh, params, pd = _setup(dp=dp, tp=tp)
        key = jax.random.key(3)
        base = np.asarray(sample_generate(
            params, pd, mesh, CFG, N_NEW, key, temperature=1.2,
            top_k=16))
        for drafter in ("ngram", "shared"):
            for nb in (2, 4):
                got = np.asarray(speculative_sample_generate(
                    params, pd, mesh, CFG, N_NEW, key, k=3,
                    temperature=1.2, top_k=16, drafter=drafter,
                    tree_branch=nb))
                np.testing.assert_array_equal(
                    got, base, err_msg=str((dp, tp, drafter, nb)))


# 99.9% chi-square quantiles, df = 1..15 (two-sample test below)
_CHI2_999 = [10.828, 13.816, 16.266, 18.467, 20.515, 22.458, 24.322,
             26.124, 27.877, 29.588, 31.264, 32.909, 34.528, 36.123,
             37.697]


def _two_sample_chi2(a, b):
    keep = (a + b) >= 10
    a2 = np.concatenate([a[keep], [a[~keep].sum()]])
    b2 = np.concatenate([b[keep], [b[~keep].sum()]])
    nz = (a2 + b2) > 0
    a2, b2 = a2[nz], b2[nz]
    k1 = np.sqrt(b2.sum() / a2.sum())
    k2 = np.sqrt(a2.sum() / b2.sum())
    stat = float((((k1 * a2 - k2 * b2) ** 2) / (a2 + b2)).sum())
    return stat, len(a2) - 1


def test_tree_rejection_sampling_chi_square_exactness():
    """Tree-speculated sampled token frequencies vs baseline
    sample_generate frequencies at matched (temperature, top_p) over
    DISJOINT seed sets — the distribution-exactness claim tested as a
    two-sample problem (the bitwise pins above use matched seeds;
    this would still catch a construction that broke exactness while
    preserving per-seed reproducibility)."""
    cfg = TransformerConfig(vocab=11, d_model=16, n_heads=2, d_head=8,
                            d_ff=32, n_layers=1, max_seq=64,
                            compute_dtype="float32")
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), cfg, mesh)
    b, s, n = 16, 6, 12
    prompts = _put(mesh, _prompts(b, s, seed=8, vocab=11))
    key = jax.random.key(7)
    base_toks, tree_toks = [], []
    for rep in range(2):
        seeds_a = np.arange(b) + 1000 * rep
        seeds_b = np.arange(b) + 1000 * rep + 500
        base = np.asarray(sample_generate(
            params, prompts, mesh, cfg, n, key, temperature=1.3,
            top_p=0.9, seeds=seeds_a))
        tree = np.asarray(speculative_sample_generate(
            params, prompts, mesh, cfg, n, key, k=3, temperature=1.3,
            top_p=0.9, seeds=seeds_b, drafter="ngram",
            tree_branch=2))
        base_toks.append(base[:, s:].ravel())
        tree_toks.append(tree[:, s:].ravel())
    a = np.bincount(np.concatenate(base_toks), minlength=11)
    bfreq = np.bincount(np.concatenate(tree_toks), minlength=11)
    stat, df = _two_sample_chi2(a.astype(np.float64),
                                bfreq.astype(np.float64))
    assert df >= 1
    crit = _CHI2_999[df - 1]
    assert stat < crit, (
        f"tree-sampled token frequencies diverge from baseline at "
        f"p<0.001: chi2={stat:.2f} > {crit} (df={df})")


# -- the sideways hop is live machinery ------------------------------

def test_tree_sideways_hop_fires_and_improves_accept_length():
    """With branch count == vocab, the siblings at each depth cover
    every token, so each primary miss before the window end MUST land
    sideways — sideways_accepted > 0 and per-pass accepted length
    strictly beats the chain's (a random-init shared drafter's
    primary chain is near-noise, so misses abound). This is the test
    that keeps the tree machinery from passing every identity pin as
    dead code."""
    cfg = TransformerConfig(vocab=11, d_model=16, n_heads=2, d_head=8,
                            d_ff=32, n_layers=2, max_seq=96,
                            compute_dtype="float32")
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), cfg, mesh)
    pd = _put(mesh, _prompts(2, 6, seed=9, vocab=11))
    base = np.asarray(greedy_generate(params, pd, mesh, cfg, 12))
    _, st_chain = speculative_generate(params, pd, mesh, cfg, 12, k=3,
                                       drafter="shared",
                                       return_stats=True)
    tree, st = speculative_generate(params, pd, mesh, cfg, 12, k=3,
                                    drafter="shared", tree_branch=11,
                                    return_stats=True)
    np.testing.assert_array_equal(np.asarray(tree), base)
    assert st["sideways_accepted"] > 0
    assert st["draft_accepted"] == (st["primary_accepted"]
                                    + st["sideways_accepted"])
    # full-vocab siblings: a window can only end at full depth or on
    # a sideways hop, so per row-step accepted length is pinned at
    # its structural value — and strictly above the chain's
    assert st["tokens_per_step"] > st_chain["tokens_per_step"]


# -- validation ------------------------------------------------------

def test_tree_branch_validation():
    mesh, params, pd = _setup()
    with pytest.raises(ValueError, match="tree_branch must be"):
        speculative_generate(params, pd, mesh, CFG, 4, k=2,
                             tree_branch=0)
    with pytest.raises(ValueError, match="draft window"):
        speculative_generate(params, pd, mesh, CFG, 4, k=1,
                             tree_branch=2)
    with pytest.raises(ValueError, match="exceeds"):
        speculative_generate(params, pd, mesh, CFG, 4, k=2,
                             tree_branch=62)
    with pytest.raises(ValueError, match="tree_branch"):
        Engine(params, mesh, CFG,
               ServeConfig(speculate_k=3, tree_branch=0))
    with pytest.raises(ValueError, match="speculate_k"):
        Engine(params, mesh, CFG,
               ServeConfig(speculate_k=1, tree_branch=2))


# -- engine ≡ single-request generate with trees on ------------------

def _serve_cfg(**over):
    sv = dict(max_rows=2, block_size=8, n_blocks=32, max_prompt=16,
              max_new=16, speculate_k=3)
    sv.update(over)
    return ServeConfig(**sv)


@pytest.mark.slow
def test_engine_tree_greedy_identity():
    """Both zero-cost drafters at b=2, one baseline pair (tier-1
    keeps engine coverage of both drafters via the sharded/chaos
    tests — default ngram — and the suffix mixed-traffic audit)."""
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    rng = np.random.default_rng(11)
    prompts = [rng.integers(0, CFG.vocab, (n,)).astype(np.int32)
               for n in (10, 7)]
    base = [np.asarray(greedy_generate(
        params, jnp.asarray(p)[None], mesh, CFG, 12))[0, len(p):]
        for p in prompts]
    for drafter in ("ngram", "suffix"):
        eng = Engine(params, mesh, CFG,
                     _serve_cfg(tree_branch=2, drafter=drafter))
        rids = [eng.submit(p, 12) for p in prompts]
        eng.run()
        for rid, b in zip(rids, base):
            np.testing.assert_array_equal(
                np.asarray(eng.queue.done[rid].tokens), b,
                err_msg=drafter)


def test_engine_tree_sampled_identity_mixed_traffic():
    """Staggered mixed greedy+sampled traffic through tree verify
    windows: every request bitwise its single-request counterpart
    (greedy_generate / sample_generate with the request's own seed
    stream) — the schedule-invariance audit with trees on."""
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    rng = np.random.default_rng(12)
    reqs = [  # (prompt, n_new, seed, temperature)
        (rng.integers(0, CFG.vocab, (9,)).astype(np.int32), 10, 3, 0.8),
        (rng.integers(0, CFG.vocab, (6,)).astype(np.int32), 12, 0, 0.0),
        (rng.integers(0, CFG.vocab, (11,)).astype(np.int32), 8, 7, 1.1),
    ]
    base = []
    for p, n, sd, T in reqs:
        if T > 0:
            out = sample_generate(
                params, jnp.asarray(p)[None], mesh, CFG, n,
                jax.random.key(0), temperature=T,
                seeds=np.asarray([sd], np.int32))
        else:
            out = greedy_generate(params, jnp.asarray(p)[None], mesh,
                                  CFG, n)
        base.append(np.asarray(out)[0, len(p):])
    eng = Engine(params, mesh, CFG,
                 _serve_cfg(tree_branch=2, drafter="suffix",
                            max_rows=2))
    # staggered admission: the third request arrives only after the
    # first completes (max_rows=2 forces queueing either way)
    rids = [eng.submit(p, n, seed=sd, temperature=T)
            for p, n, sd, T in reqs]
    eng.run()
    for rid, b in zip(rids, base):
        np.testing.assert_array_equal(
            np.asarray(eng.queue.done[rid].tokens), b)


@pytest.mark.slow
def test_engine_tree_mixed_quant_containment():
    """Tree windows on a kv_quant='mixed' engine: the fp co-batch
    row stays bitwise greedy_generate while an int8 row rides the
    same tree step (the r10 containment pin, through trees —
    relocation must move every written arena, scale pages
    included)."""
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    rng = np.random.default_rng(17)
    p_fp = rng.integers(0, CFG.vocab, (9,)).astype(np.int32)
    p_q8 = rng.integers(0, CFG.vocab, (8,)).astype(np.int32)
    base = np.asarray(greedy_generate(
        params, jnp.asarray(p_fp)[None], mesh, CFG, 10))[0, 9:]
    eng = Engine(params, mesh, CFG,
                 _serve_cfg(tree_branch=2, drafter="suffix",
                            kv_quant="mixed"))
    r1 = eng.submit(p_fp, 10)
    r2 = eng.submit(p_q8, 10, quant=True)
    eng.run()
    np.testing.assert_array_equal(
        np.asarray(eng.queue.done[r1].tokens), base)
    assert len(eng.queue.done[r2].tokens) == 10


def test_engine_tree_identity_sharded():
    dp, tp = 2, 2
    mesh = make_model_mesh(dp=dp, tp=tp, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    rng = np.random.default_rng(13)
    prompts = [rng.integers(0, CFG.vocab, (8,)).astype(np.int32)
               for _ in range(2)]
    base = [np.asarray(greedy_generate(
        params, _put(mesh, np.broadcast_to(p, (dp, 8)).copy()), mesh,
        CFG, 10))[0, 8:] for p in prompts]
    eng = Engine(params, mesh, CFG,
                 _serve_cfg(tree_branch=2, max_rows=dp))
    rids = [eng.submit(p, 10) for p in prompts]
    eng.run()
    for rid, b in zip(rids, base):
        np.testing.assert_array_equal(
            np.asarray(eng.queue.done[rid].tokens), b)


@pytest.mark.slow
def test_engine_tree_identity_sharded_exhaustive():
    """dp-only mesh + wider branch counts (the tier-1 tests keep the
    dp×tp mesh and b=2)."""
    for dp, tp in ((2, 1),):
        mesh = make_model_mesh(dp=dp, tp=tp, sp=1)
        params = init_params(jax.random.key(0), CFG, mesh)
        rng = np.random.default_rng(13)
        prompts = [rng.integers(0, CFG.vocab, (8,)).astype(np.int32)
                   for _ in range(2)]
        base = [np.asarray(greedy_generate(
            params, _put(mesh, np.broadcast_to(p, (dp, 8)).copy()),
            mesh, CFG, 10))[0, 8:] for p in prompts]
        for nb in (2, 3):
            eng = Engine(params, mesh, CFG,
                         _serve_cfg(tree_branch=nb, max_rows=dp))
            rids = [eng.submit(p, 10) for p in prompts]
            eng.run()
            for rid, b in zip(rids, base):
                np.testing.assert_array_equal(
                    np.asarray(eng.queue.done[rid].tokens), b,
                    err_msg=str((dp, tp, nb)))


# -- chaos: tree sites -----------------------------------------------

def test_tree_build_die_site():
    mesh, params, pd = _setup()
    plan = chaos.FaultPlan(
        schedule={"die:decode.spec.tree.build": (0,)})
    with chaos.inject(plan):
        with pytest.raises(chaos.InjectedDeath):
            speculative_generate(params, pd, mesh, CFG, N_NEW, k=3,
                                 tree_branch=2)
        out = speculative_generate(params, pd, mesh, CFG, N_NEW, k=3,
                                   tree_branch=2)
    assert np.asarray(out).shape == (2, 18)
    assert plan.fired("die", "decode.spec.tree.build") == 1
    # the chain path never reaches the tree build boundary
    with chaos.inject(chaos.FaultPlan(
            schedule={"die:decode.spec.tree.build": (0,)})) as p2:
        speculative_generate(params, pd, mesh, CFG, N_NEW, k=3)
    assert p2.fired("die", "decode.spec.tree.build") == 0


def test_tree_verify_stats_sdc_skews_counters_not_tokens():
    """SDC at the tree stats readback: committed tokens are bitwise
    untouched (tokens never pass through the stats vector), telemetry
    stays JSON-serializable even when skewed."""
    import json
    mesh, params, pd = _setup()
    base = np.asarray(speculative_generate(params, pd, mesh, CFG,
                                           N_NEW, k=3, tree_branch=2))
    plan = chaos.FaultPlan(
        schedule={"corrupt:decode.spec.tree.verify": (0,)})
    with chaos.inject(plan):
        out, st = speculative_generate(params, pd, mesh, CFG, N_NEW,
                                       k=3, tree_branch=2,
                                       return_stats=True)
    assert plan.fired("corrupt", "decode.spec.tree.verify") == 1
    np.testing.assert_array_equal(np.asarray(out), base)
    json.dumps(st)


def test_tree_clean_armed_run_bit_identical():
    """An armed plan whose probes all fire as delays leaves
    tree-speculated output bitwise the unarmed run — the standing
    clean-armed pin, extended to the tree sites."""
    mesh, params, pd = _setup()
    base = np.asarray(speculative_generate(params, pd, mesh, CFG,
                                           N_NEW, k=3, tree_branch=2))
    plan = chaos.FaultPlan(rates={"delay:decode.spec.tree.*": 1.0},
                           delay_s=0.001)
    with chaos.inject(plan):
        out = speculative_generate(params, pd, mesh, CFG, N_NEW, k=3,
                                   tree_branch=2)
    np.testing.assert_array_equal(np.asarray(out), base)
    assert plan.fired("delay", "decode.spec.tree.build") == 1


def test_serve_tree_fork_die_reissues_to_survivor():
    """Engine dies at the serve.spec.tree.fork boundary mid-serve:
    leases expire and a second engine pointed at the same queue
    completes every request token-identically — the dead-engine
    drill through the tree path."""
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    rng = np.random.default_rng(15)
    prompts = [rng.integers(0, CFG.vocab, (8,)).astype(np.int32)
               for _ in range(2)]
    base = [np.asarray(greedy_generate(
        params, jnp.asarray(p)[None], mesh, CFG, 10))[0, 8:]
        for p in prompts]
    q = RequestQueue(lease_s=0.05)
    sv = _serve_cfg(tree_branch=2, drafter="suffix")
    eng1 = Engine(params, mesh, CFG, sv, queue=q)
    rids = [eng1.submit(p, 10) for p in prompts]
    plan = chaos.FaultPlan(
        schedule={"die:serve.spec.tree.fork": (2,)})
    with chaos.inject(plan):
        with pytest.raises(chaos.InjectedDeath):
            eng1.run()
    assert plan.fired("die", "serve.spec.tree.fork") == 1
    time.sleep(0.06)          # leases expire
    eng2 = Engine(params, mesh, CFG, sv, queue=q)
    eng2.run()
    for rid, b in zip(rids, base):
        np.testing.assert_array_equal(
            np.asarray(q.done[rid].tokens), b)


def test_serve_tree_fork_delay_site_clean():
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    rng = np.random.default_rng(16)
    p = rng.integers(0, CFG.vocab, (8,)).astype(np.int32)
    base = np.asarray(greedy_generate(
        params, jnp.asarray(p)[None], mesh, CFG, 10))[0, 8:]
    eng = Engine(params, mesh, CFG,
                 _serve_cfg(tree_branch=2))
    plan = chaos.FaultPlan(rates={"delay:serve.spec.tree.fork": 1.0},
                           delay_s=0.001)
    with chaos.inject(plan):
        rid = eng.submit(p, 10)
        eng.run()
    assert plan.fired("delay", "serve.spec.tree.fork") >= 1
    np.testing.assert_array_equal(
        np.asarray(eng.queue.done[rid].tokens), base)
