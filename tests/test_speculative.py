"""Speculative multi-token decode tests.

The load-bearing invariant: greedy speculative output is
TOKEN-IDENTICAL to the baseline greedy decode for any verify width k
and any draft depth — acceptance logic changes the cost structure
(weights read once per accepted window), never the sampled sequence.
Plus: the fused single-token decode-step kernel reproduces the unfused
step, and the acceptance telemetry flows through icikit.obs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from icikit import obs
from icikit.models.transformer import (
    TransformerConfig,
    init_params,
    speculative_generate,
)
from icikit.models.transformer.decode import greedy_generate
from icikit.models.transformer.model import make_model_mesh

CFG = TransformerConfig(vocab=61, d_model=32, n_heads=4, d_head=8,
                        d_ff=64, n_layers=4, max_seq=32,
                        compute_dtype="float32")


def _prompt(mesh, b=3, s=8, vocab=61, seed=0):
    rng = np.random.default_rng(seed)
    return jax.device_put(
        jnp.asarray(rng.integers(0, vocab, (b, s)), jnp.int32),
        NamedSharding(mesh, P("dp", None)))


@pytest.mark.parametrize("k", [1, 2, 4])
@pytest.mark.parametrize("draft_layers", [1, 2, 4])
def test_speculative_identical_to_greedy(k, draft_layers):
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    pd = _prompt(mesh)
    base = np.asarray(greedy_generate(params, pd, mesh, CFG, n_new=10))
    got = np.asarray(speculative_generate(
        params, pd, mesh, CFG, 10, k=k, draft_layers=draft_layers))
    np.testing.assert_array_equal(got, base)


@pytest.mark.parametrize("dp,tp", [(1, 4), (2, 2)])
@pytest.mark.parametrize("variant", ["dense", "rope", "vocab_parallel"])
def test_speculative_identity_sharded(dp, tp, variant):
    over = {"rope": {"pos_encoding": "rope"},
            "vocab_parallel": {"vocab_parallel": True},
            "dense": {}}[variant]
    cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4, d_head=8,
                            d_ff=64, n_layers=3, max_seq=32,
                            compute_dtype="float32", **over)
    mesh = make_model_mesh(dp=dp, tp=tp, sp=1)
    params = init_params(jax.random.key(0), cfg, mesh)
    pd = _prompt(mesh, b=4, s=6, vocab=64, seed=1)
    base = np.asarray(greedy_generate(params, pd, mesh, cfg, n_new=8))
    got = np.asarray(speculative_generate(params, pd, mesh, cfg, 8,
                                          k=3, draft_layers=2))
    np.testing.assert_array_equal(got, base)


def test_speculative_gqa_identity():
    cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4, d_head=8,
                            d_ff=64, n_layers=3, max_seq=32,
                            compute_dtype="float32", n_kv_heads=2)
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), cfg, mesh)
    pd = _prompt(mesh, b=2, s=6, vocab=64, seed=2)
    base = np.asarray(greedy_generate(params, pd, mesh, cfg, n_new=8))
    got = np.asarray(speculative_generate(params, pd, mesh, cfg, 8,
                                          k=3, draft_layers=1))
    np.testing.assert_array_equal(got, base)


def test_full_depth_drafter_accepts_everything():
    """draft_layers == n_layers makes the drafter the exact model:
    every draft matches, acceptance = 1.0, and each verify step
    commits a full k-token window — the mechanical upper bound the
    acceptance × cost model is anchored to."""
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    pd = _prompt(mesh)
    # drafter="shared" explicitly: the r11 "auto" flip resolves the
    # no-head fallback to "ngram", and this test is ABOUT the shared
    # drafter's full-depth exactness bound
    _, st = speculative_generate(params, pd, mesh, CFG, 10, k=4,
                                 draft_layers=CFG.n_layers,
                                 drafter="shared",
                                 return_stats=True)
    assert st["acceptance_rate"] == 1.0
    assert st["tokens_per_step"] == 4.0
    # 9 post-prefill tokens at 4/step -> 3 verify iterations
    assert st["verify_steps"] == 3


def test_k1_degenerates_to_single_token():
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    pd = _prompt(mesh)
    _, st = speculative_generate(params, pd, mesh, CFG, 10, k=1,
                                 draft_layers=1, return_stats=True)
    assert st["verify_steps"] == 9          # one token per pass
    assert st["draft_proposed"] == 0
    assert st["acceptance_rate"] == 1.0     # vacuous: nothing proposed


def test_speculative_counters_flow_through_obs():
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    pd = _prompt(mesh)
    with obs.session(ring := obs.RingSink()) as s:
        with obs.span("test.decode"):
            speculative_generate(params, pd, mesh, CFG, 6, k=2,
                                 draft_layers=2)
        snap = s.registry.snapshot()
    counters = snap.get("counters", snap)
    keys = set(counters)
    assert {"decode.spec.verify_steps", "decode.spec.draft_proposed",
            "decode.spec.draft_accepted"} <= keys
    # the span stack closed cleanly around the jitted loop
    names = [ev.get("name") for ev in s.trace.snapshot()
             if isinstance(ev, dict)]
    assert any(n == "decode.speculative" for n in names)


def test_speculative_validation():
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), CFG, mesh)
    pd = jnp.zeros((1, 8), jnp.int32)
    with pytest.raises(ValueError, match="k must be"):
        speculative_generate(params, pd, mesh, CFG, 4, k=0)
    with pytest.raises(ValueError, match="draft_layers"):
        speculative_generate(params, pd, mesh, CFG, 4, k=2,
                             draft_layers=99)
    with pytest.raises(ValueError, match="max_seq"):
        # 8 + 22 + 3 > 32
        speculative_generate(params, pd, mesh, CFG, 22, k=4,
                             draft_layers=1)
    moe_cfg = TransformerConfig(vocab=61, d_model=32, n_heads=4,
                                d_head=8, d_ff=64, n_layers=2,
                                max_seq=32, compute_dtype="float32",
                                n_experts=2)
    moe_params = init_params(jax.random.key(0), moe_cfg, mesh)
    with pytest.raises(ValueError, match="MoE"):
        speculative_generate(moe_params, pd, mesh, moe_cfg, 4, k=2)
