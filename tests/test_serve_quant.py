"""Serving on the int8 KV path: engine/generate identity, the
mixed-precision co-batch containment pin, and int8 page integrity.

The two load-bearing contracts (DECODE.md "Quantized decode"):

- an ``"int8"`` engine is greedy-token-identical PER REQUEST to int8
  ``greedy_generate`` (the engine-vs-generate identity bar, carried
  over from the fp engine unchanged);
- on a ``"mixed"`` engine, fp requests co-batched with a quantized
  request are BITWISE unchanged vs an engine that never saw a
  quantized request — containment is structural (separate arenas, one
  allocator), not probabilistic.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from icikit.models.transformer import (
    TransformerConfig,
    greedy_generate,
    init_params,
)
from icikit.models.transformer.model import make_model_mesh
from icikit.serve import Engine, RequestQueue, ServeConfig

CFG = TransformerConfig(vocab=61, d_model=32, n_heads=4, d_head=8,
                        d_ff=64, n_layers=2, max_seq=64,
                        compute_dtype="float32")
QCFG = dataclasses.replace(CFG, decode_quant="int8")
SV = dict(max_rows=2, block_size=4, n_blocks=16, max_prompt=16,
          max_new=16)


def _mesh(dp=1, tp=1):
    return make_model_mesh(dp=dp, tp=tp, sp=1)


def _params(mesh, cfg=CFG):
    return init_params(jax.random.key(0),
                       dataclasses.replace(cfg, decode_quant="none"),
                       mesh)


def _prompts(n=3, seed=1):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, CFG.vocab, (s,)).astype(np.int32)
            for s in rng.integers(3, 12, size=n)]


def _run(cfg, mesh, quant_flags=None, n_new=10, **sv_over):
    prompts = _prompts()
    quant_flags = quant_flags or [False] * len(prompts)
    eng = Engine(_params(mesh, cfg), mesh, cfg,
                 ServeConfig(**{**SV, **sv_over}))
    rids = [eng.submit(p, n_new, quant=qf)
            for p, qf in zip(prompts, quant_flags)]
    eng.run()
    return [tuple(eng.queue.done[r].tokens) for r in rids], eng


@pytest.mark.parametrize("speculate_k", [1, 3])
def test_int8_engine_identity_to_int8_generate(speculate_k):
    mesh = _mesh()
    params = _params(mesh)
    outs, eng = _run(QCFG, mesh, speculate_k=speculate_k)
    assert eng.kv_mode == "int8"
    assert eng.pool.kc is None          # no fp arena on the int8 path
    for p, toks in zip(_prompts(), outs):
        want = np.asarray(greedy_generate(
            params, jnp.asarray(p)[None], mesh, QCFG, 10))[0, len(p):]
        assert tuple(int(t) for t in want) == toks


def test_int8_engine_identity_across_meshes():
    cfg = dataclasses.replace(QCFG, vocab=64)
    mesh1 = _mesh()
    base, _ = _run(cfg, mesh1)
    mesh = _mesh(dp=2, tp=2)
    got, _ = _run(cfg, mesh)
    assert got == base


def test_mixed_cobatch_fp_rows_bitwise_unchanged():
    """THE containment pin: fp requests sharing steps with a quantized
    request produce bitwise the tokens an all-fp engine produces."""
    mesh = _mesh()
    base, _ = _run(CFG, mesh)                                  # all fp
    mixed, eng = _run(CFG, mesh, quant_flags=[False, True, False],
                      kv_quant="mixed")
    assert eng.kv_mode == "mixed"
    assert mixed[0] == base[0] and mixed[2] == base[2]
    # and the quantized row is served from the int8 arena (its row
    # really shared steps — max_rows=2 forces co-batching)
    assert eng.pool.qkc is not None


def test_mixed_quant_row_matches_int8_kv_semantics():
    """A mixed engine's quantized row reads dequantized int8 pages —
    same KV semantics as the pure-int8 pool (weights stay fp in mixed,
    so compare against a kv-only reference: the fp engine's output may
    differ, the int8-KV effect is what routes)."""
    mesh = _mesh()
    mixed, _ = _run(CFG, mesh, quant_flags=[True, True, True],
                    kv_quant="mixed")
    again, _ = _run(CFG, mesh, quant_flags=[True, True, True],
                    kv_quant="mixed")
    assert mixed == again                  # deterministic routing


def test_quant_request_on_fp_engine_fails_loudly():
    mesh = _mesh()
    eng = Engine(_params(mesh), mesh, CFG, ServeConfig(**SV))
    rid = eng.submit(_prompts()[0], 6, quant=True)
    eng.run()
    assert rid in eng.queue.failed
    assert "no int8 KV arena" in eng.queue.failed[rid].error


def test_engine_validates_quant_configs():
    mesh = _mesh()
    with pytest.raises(ValueError, match="mixed"):
        Engine(_params(mesh), mesh, QCFG,
               ServeConfig(**SV, kv_quant="mixed"))
    with pytest.raises(ValueError, match="int8 KV"):
        Engine(_params(mesh), mesh, QCFG,
               ServeConfig(**SV, kv_quant="none"))


def test_int8_engine_seal_verify_catches_page_and_scale_flips():
    """Sealed-page integrity on the quantized payload: a flipped int8
    byte AND a flipped scale value both fail the verify — the checksum
    covers exactly the bytes the request decodes from."""
    mesh = _mesh()
    eng = Engine(_params(mesh), mesh, QCFG,
                 ServeConfig(**SV, integrity="pages"))
    rid = eng.submit(np.arange(8, dtype=np.int32), 8)
    eng.run()
    assert rid in eng.queue.done
    pool = eng.pool
    # re-seal a fresh owner by hand to drill the q8 digest path
    table = pool.allocators[0].alloc("drill", 2)
    pool.seal(0, table[0])
    assert pool.verify("drill", 0) == []
    flipped = pool.read_page(0, table[0], 0).copy()
    flipped[0, 0] ^= 1                     # one int8 bit
    pool.poke_page(0, table[0], 0, flipped)
    assert pool.verify("drill", 0) == [0]
    # restore, then flip a SCALE value instead
    flipped[0, 0] ^= 1
    pool.poke_page(0, table[0], 0, flipped)
    assert pool.verify("drill", 0) == []
    ksc = list(pool.ksc)
    ksc[0] = ksc[0].at[0, table[0], 0, 0].add(1.0)
    pool.ksc = tuple(ksc)
    assert pool.verify("drill", 0) == [0]


def test_int8_engine_chaos_kv_page_drill_contained():
    """The serve.kv.page SDC drill on the int8 arena: the victim
    fails its sealed-page verify, retries on fresh blocks, completes;
    co-batched outputs are unchanged."""
    from icikit import chaos
    mesh = _mesh()
    params = _params(mesh)
    clean, _ = _run(QCFG, mesh, n_new=12, integrity="pages")
    queue = RequestQueue()
    eng = Engine(params, mesh, QCFG,
                 ServeConfig(**SV, integrity="pages"), queue=queue)
    prompts = _prompts()
    rids = [eng.submit(p, 12) for p in prompts]
    plan = chaos.FaultPlan(schedule={"corrupt:serve.kv.page": (0,)})
    with chaos.inject(plan):
        eng.run()
    assert plan.fired("corrupt", "serve.kv.page") == 1
    assert all(r in queue.done for r in rids)
    got = [tuple(queue.done[r].tokens) for r in rids]
    assert got == clean
    assert any(queue.done[r].attempts > 1 for r in rids)


def test_int8_engine_prefix_cache_config_is_identity_safe():
    """prefix_cache=True on an int8 engine must not perturb the
    engine≡int8-generate parity bar: quantized pages never enter the
    index (a cached q8 block cannot reproduce the raw prompt-column
    attention the deployed prefill computes), so repeated prompts
    admit as recomputes and tokens stay exact."""
    mesh = _mesh()
    params = _params(mesh)
    prompt = np.arange(3, 11, dtype=np.int32)
    eng = Engine(params, mesh, QCFG,
                 ServeConfig(**SV, prefix_cache=True))
    rids = [eng.submit(prompt, 10) for _ in range(3)]
    eng.run()
    want = np.asarray(greedy_generate(
        params, jnp.asarray(prompt)[None], mesh, QCFG, 10))[0, 8:]
    for rid in rids:
        np.testing.assert_array_equal(
            np.asarray(eng.queue.done[rid].tokens), want)
    st = eng.prefix_stats()
    assert st["hits"] == 0 and st["misses"] == 0   # q8 never indexes
    assert sum(a.n_cached for a in eng.pool.allocators) == 0


def test_mixed_engine_fp_rows_prefix_hit_with_q8_cobatch():
    """On a mixed engine the fp side keeps full prefix caching: a
    repeated fp prompt hits while a quantized row co-batches, and the
    fp tokens equal the all-fp engine's (containment + caching
    compose). The q8 row's pages stay out of the index."""
    mesh = _mesh()
    params = _params(mesh)
    rng = np.random.default_rng(31)
    fp_p = rng.integers(0, CFG.vocab, (8,)).astype(np.int32)
    q_p = rng.integers(0, CFG.vocab, (8,)).astype(np.int32)
    base = np.asarray(greedy_generate(
        params, jnp.asarray(fp_p)[None], mesh, CFG, 10))[0, 8:]
    eng = Engine(params, mesh, CFG,
                 ServeConfig(**SV, kv_quant="mixed"))
    r0 = eng.submit(fp_p, 10)
    eng.run()                              # seed the fp-side cache
    r1 = eng.submit(fp_p, 10)              # fp repeat: full hit
    rq = eng.submit(q_p, 10, quant=True)   # co-batched quantized row
    eng.run()
    for rid in (r0, r1):
        np.testing.assert_array_equal(
            np.asarray(eng.queue.done[rid].tokens), base)
    assert eng.queue.done[rq].state == "done"
    st = eng.prefix_stats()
    assert st["hits"] == 1 and st["full_hits"] == 1
