"""Coordinator-side fleet collector (`icikit.obs.aggregate`): batch
ingestion honesty, the clock-aligned multi-process trace merge, and
the aggregated watch/roster surfaces.

The merge claims under test:

- a constant per-source clock shift (the handshake offset) preserves
  per-(pid, tid) monotonicity, so the merged file stays checker-valid
  for ANY offset assignment (property test);
- colliding pids (two in-process sources sharing one OS pid) are
  remapped onto fresh tracks with ``process_name`` metadata — B/E and
  async b/e discipline survive the interleave;
- a killed engine's dangling spans are exactly what
  ``chrome.close_dangling`` heals at export: the merged file on disk
  passes ``python -m icikit.obs.check``;
- ``cross_process_trees`` counts ``serve.req`` trees whose events
  span ≥2 processes — the prefill→handoff→decode acceptance shape.
"""

import json

import numpy as np
import pytest

from icikit.fleet.telemetry import chain_bloom, payload_digest
from icikit.obs import chrome
from icikit.obs.aggregate import FleetCollector
from icikit.obs.metrics import Registry


def _send(col, source, seq, trace=(), events=(), metrics=None,
          dropped=0, offset_us=None, digest=None):
    payload = json.dumps({"events": list(events),
                          "trace": list(trace),
                          "metrics": metrics}).encode()
    reply, _ = col.handle("telemetry.batch", {
        "source": source, "seq": seq, "offset_us": offset_us,
        "digest": digest if digest is not None
        else payload_digest(payload),
        "dropped": dropped}, (payload,))
    return reply


def _hello(col, source, pid, role="engine"):
    reply, _ = col.handle("telemetry.hello",
                          {"source": source, "role": role,
                           "pid": pid}, ())
    return reply


def _spans(pid, tid, t0, names=("outer", "inner")):
    """A nested B/E pair stack starting at ``t0`` (local clock)."""
    evs = []
    t = t0
    for n in names:
        evs.append({"ph": "B", "name": n, "pid": pid, "tid": tid,
                    "ts": t})
        t += 10
    for n in reversed(names):
        evs.append({"ph": "E", "name": n, "pid": pid, "tid": tid,
                    "ts": t})
        t += 10
    return evs


# -- ingestion honesty ----------------------------------------------

def test_hello_echoes_collector_clock_and_registers_source():
    col = FleetCollector()
    r = _hello(col, "e0", pid=4242, role="decode")
    assert isinstance(r["clock_us"], int)
    st = col.stats()
    assert st["sources"]["e0"]["pid"] == 4242
    assert st["sources"]["e0"]["role"] == "decode"


def test_digest_mismatch_drops_without_parsing():
    col = FleetCollector()
    # payload is not even JSON — if the collector tried to parse a
    # digest-failed batch this would raise instead of counting
    rotten = b"\x00\xffnot json at all"
    reply, _ = col.handle("telemetry.batch", {
        "source": "e0", "seq": 1, "offset_us": 0,
        "digest": payload_digest(b"what the sender hashed"),
        "dropped": 0}, (rotten,))
    assert reply["accepted"] is False
    st = col.stats()
    assert st["corrupt_frames"] == 1
    assert st["sources"]["e0"]["events"] == 0
    v = col.verdict()
    assert v["healthy"] is False
    assert v["telemetry_loss"] == [
        {"source": "e0", "kind": "corrupt_frames", "n": 1}]


def test_sequence_gap_counts_lost_batches():
    col = FleetCollector()
    _send(col, "e0", seq=1)
    _send(col, "e0", seq=4)          # 2 and 3 never arrived
    st = col.stats()
    assert st["lost_batches"] == 2
    assert st["sources"]["e0"]["batches"] == 2
    assert {"source": "e0", "kind": "lost_batches", "n": 2} \
        in col.verdict()["telemetry_loss"]


def test_sender_reported_drops_surface_in_verdict():
    col = FleetCollector()
    # the header's dropped counter is cumulative sender-side — the
    # collector keeps the high-water mark, not the sum
    _send(col, "e0", seq=1, dropped=3)
    _send(col, "e0", seq=2, dropped=5)
    st = col.stats()
    assert st["dropped"] == 5
    v = col.verdict()
    assert v["healthy"] is False
    assert {"source": "e0", "kind": "dropped", "n": 5} \
        in v["telemetry_loss"]


def test_clean_stream_is_healthy():
    col = FleetCollector()
    _send(col, "e0", seq=1, events=[{"event": "x"}])
    _send(col, "e0", seq=2, events=[{"event": "y"}])
    v = col.verdict()
    assert v["telemetry_loss"] == []
    assert v["healthy"] is True
    assert col.stats()["sources"]["e0"]["events"] == 2


def test_unknown_telemetry_op_rejected():
    col = FleetCollector()
    with pytest.raises(ValueError, match="unknown telemetry op"):
        col.handle("telemetry.bogus", {}, ())


# -- trace merge ----------------------------------------------------

def test_merge_shifts_sources_into_collector_domain():
    col = FleetCollector()
    _hello(col, "e0", pid=111)
    # e0's local clock runs 1000us behind the collector's
    _send(col, "e0", seq=1, offset_us=1000,
          trace=_spans(111, 1, t0=0))
    local = _spans(999, 1, t0=500)
    merged = col.merge_traces(local)
    assert chrome.validate(merged) == []
    shifted = [ev["ts"] for ev in merged
               if ev.get("pid") == 111 and "ts" in ev]
    assert shifted == [1000, 1010, 1020, 1030]
    # local (collector-domain) events are never shifted
    assert [ev["ts"] for ev in merged if ev.get("pid") == 999] \
        == [500, 510, 520, 530]


def test_merge_remaps_colliding_pids_onto_fresh_tracks():
    """Two in-process test "engines" share one OS pid; the merge gives
    each its own track (real worker processes never collide)."""
    col = FleetCollector()
    _hello(col, "a", pid=1234, role="prefill")
    _hello(col, "b", pid=1234, role="decode")
    _send(col, "a", seq=1, offset_us=0, trace=_spans(1234, 1, t0=0))
    _send(col, "b", seq=1, offset_us=0, trace=_spans(1234, 1, t0=5))
    local = _spans(1234, 1, t0=100)
    merged = col.merge_traces(local)
    assert chrome.validate(merged) == []
    pids = {ev.get("pid") for ev in merged if ev.get("ph") != "M"}
    assert len(pids) == 3, pids          # local + two remapped tracks
    names = {ev["args"]["name"] for ev in merged
             if ev.get("ph") == "M"
             and ev.get("name") == "process_name"}
    assert names == {"prefill:a", "decode:b"}
    # the local track keeps its true pid; sources moved off it
    assert 1234 in pids


def test_merge_property_arbitrary_offsets_stay_checker_valid():
    """The load-bearing invariant: a constant per-source shift plus a
    stable sort keeps EVERY track internally monotonic, so the merged
    file is checker-valid for any clock-offset assignment."""
    rng = np.random.default_rng(0)
    for trial in range(20):
        col = FleetCollector()
        n_sources = int(rng.integers(1, 4))
        for i in range(n_sources):
            name = f"e{i}"
            _hello(col, name, pid=100 + i)
            off = int(rng.integers(-50_000, 50_000))
            trace = []
            for tid in range(int(rng.integers(1, 3))):
                trace += _spans(100 + i, tid,
                                t0=int(rng.integers(0, 1000)))
                # async request-tree events ride the same clock
                t = int(rng.integers(0, 1000))
                trace += [
                    {"ph": "b", "name": "serve.req", "cat": "serve.req",
                     "id": f"r{i}-{tid}", "pid": 100 + i, "tid": tid,
                     "ts": t},
                    {"ph": "n", "name": "serve.req.claimed",
                     "cat": "serve.req", "id": f"r{i}-{tid}",
                     "pid": 100 + i, "tid": tid, "ts": t + 5},
                    {"ph": "e", "name": "serve.req", "cat": "serve.req",
                     "id": f"r{i}-{tid}", "pid": 100 + i, "tid": tid,
                     "ts": t + 9},
                ]
            _send(col, name, seq=1, offset_us=off, trace=trace)
        merged = col.merge_traces(_spans(999, 0, t0=0))
        problems = chrome.validate(merged)
        assert problems == [], (trial, problems)
        # per-(pid, tid) timestamps are non-decreasing in list order
        last = {}
        for ev in merged:
            if ev.get("ph") == "M" or "ts" not in ev:
                continue
            key = (ev["pid"], ev.get("tid"))
            assert ev["ts"] >= last.get(key, float("-inf"))
            last[key] = ev["ts"]


def test_killed_engine_dangling_spans_close_at_export(tmp_path):
    """An engine killed mid-trace leaves unclosed B and async b spans;
    the merged list is honestly invalid in memory, and the EXPORT path
    (close_dangling) writes a checker-valid file — the acceptance
    pipeline for a run that survived an engine death."""
    col = FleetCollector()
    _hello(col, "dead0", pid=77)
    trace = [
        {"ph": "B", "name": "decode.step", "pid": 77, "tid": 1,
         "ts": 10},
        {"ph": "b", "name": "serve.req", "cat": "serve.req",
         "id": "r-dead", "pid": 77, "tid": 1, "ts": 12},
        # ... killed here: no E, no e
    ]
    _send(col, "dead0", seq=1, offset_us=0, trace=trace)
    merged = col.merge_traces(_spans(999, 0, t0=0))
    assert chrome.validate(merged) != []        # honest: dangling
    path = tmp_path / "merged.json"
    chrome.export(path, merged)
    assert chrome.validate(str(path)) == []     # healed on disk
    obj = json.load(open(path))
    closed = [ev for ev in obj["traceEvents"]
              if (ev.get("args") or {}).get("closed_by") == "export"]
    assert {ev["ph"] for ev in closed} == {"E", "e"}


def test_cross_process_trees_counts_spanning_trees_only():
    base = {"cat": "serve.req", "id": "r1"}
    spanning = [
        {"ph": "b", "name": "serve.req", "pid": 1, "tid": 0, "ts": 0,
         **base},
        {"ph": "n", "name": "serve.req.claimed", "pid": 2, "tid": 0,
         "ts": 5, **base},
        {"ph": "n", "name": "serve.req.handoff", "pid": 3, "tid": 0,
         "ts": 8, **base},
        {"ph": "e", "name": "serve.req", "pid": 1, "tid": 0, "ts": 9,
         **base},
    ]
    single = [
        {"ph": "b", "name": "serve.req", "cat": "serve.req",
         "id": "r2", "pid": 4, "tid": 0, "ts": 0},
        {"ph": "e", "name": "serve.req", "cat": "serve.req",
         "id": "r2", "pid": 4, "tid": 0, "ts": 3},
    ]
    events = spanning + single
    assert FleetCollector.cross_process_trees(events) == 1
    # excluding the coordinator's pid: the tree still spans the two
    # ENGINE processes (2 and 3)
    assert FleetCollector.cross_process_trees(
        events, exclude_pid=1) == 1
    # excluding an engine pid leaves only coordinator+one engine
    assert FleetCollector.cross_process_trees(
        [e for e in spanning if e["pid"] != 3], exclude_pid=1) == 0


# -- roster + registry surfaces -------------------------------------

def test_update_report_rolls_up_occupancy_and_token_rate():
    reg = Registry()
    col = FleetCollector(registry=reg, rate_window_s=0.0)
    col.update_report("e0", {"occupancy": 0.75, "tokens": 0})
    col.update_report("e1", {"occupancy": 0.25, "tokens": 0})
    col.maybe_poll()                 # baseline window
    col.update_report("e0", {"occupancy": 0.75, "tokens": 90})
    col.update_report("e1", {"occupancy": 0.25, "tokens": 10})
    col.maybe_poll()
    snap = reg.snapshot()
    assert snap["gauges"]["fleet.engine.e0.occupancy"] == 0.75
    assert snap["gauges"]["fleet.engine.e1.occupancy"] == 0.25
    assert snap["gauges"]["fleet.tokens_per_s"] > 0.0


def test_metrics_snapshot_gauges_mirrored_per_engine():
    reg = Registry()
    col = FleetCollector(registry=reg)
    _send(col, "e0", seq=1,
          metrics={"gauges": {"serve.occupancy_rows": 0.5},
                   "counters": {}, "histograms": {}})
    snap = reg.snapshot()
    assert snap["gauges"][
        "fleet.engine.e0.serve.occupancy_rows"] == 0.5


def test_observe_latency_feeds_fleet_histograms():
    reg = Registry()
    col = FleetCollector(registry=reg)
    col.observe_latency("fleet.claim_ms", 2.5)
    col.observe_latency("fleet.claim_ms", 3.5)
    h = reg.snapshot()["histograms"]["fleet.claim_ms"]
    assert h["count"] == 2 and h["sum"] == 6.0


def test_straggler_engine_alerts_with_source_and_callback():
    """One engine's TPOT at k× the fleet median raises an `obs.alert`
    stamped with THAT engine as source; the coordinator's on_alert
    listener hears it, and a listener bug never propagates."""
    heard = []

    def listener(a):
        heard.append(a)
        raise RuntimeError("listener bug must not stall the reaper")

    col = FleetCollector(poll_interval_s=0.0, min_count=4,
                         straggler_factor=3.0, on_alert=listener)
    for _ in range(6):
        col.observe_slo("e0", {"tpot_ms": 1.0})
        col.observe_slo("e1", {"tpot_ms": 1.0})
        col.observe_slo("e2", {"tpot_ms": 50.0})   # the straggler
    alerts = col.maybe_poll()
    stragglers = [a for a in alerts
                  if a.watch.startswith("straggler")]
    assert len(stragglers) == 1
    assert stragglers[0].source == "e2"
    assert stragglers[0].metric == "serve.tpot_ms"
    assert heard == alerts
    v = col.verdict()
    assert v["healthy"] is False
    assert sorted(v["sources"]) == ["e0", "e1", "e2"]


def test_resident_summaries_roundtrip():
    col = FleetCollector()
    s0 = chain_bloom(["a", "b", "c"])
    col.update_resident("e0", s0)
    col.update_resident("e1", None)      # engine with nothing resident
    assert col.resident_summaries() == {"e0": s0}
    assert col.stats()["sources"]["e0"]["resident_n"] == 3
