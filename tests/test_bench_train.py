"""Training-throughput bench: record production and peak calibration."""

from icikit.bench.train import measure_peak, run_bench


def test_run_bench_tiny():
    rec = run_bench("tiny", dp=1, tp=1, sp=1, batch=2, steps=2, warmup=1)
    assert rec["unit"] == "tokens/s" and rec["value"] > 0
    assert rec["step_ms"] > 0  # tflops rounds to 0.00 on CPU-tiny
    assert "noremat" not in rec["metric"]


def test_run_bench_tiny_noremat_tag():
    rec = run_bench("tiny", dp=1, tp=1, sp=1, batch=2, steps=2, warmup=1,
                    remat=False)
    assert rec["metric"].endswith("_noremat")


def test_measure_peak_small():
    """The calibration harness itself (tiny shapes — CPU-runnable)."""
    flops = measure_peak(n=256, iters=2)
    assert flops > 0
