"""Training-throughput bench: record production and peak calibration."""

from icikit.bench.train import measure_peak, run_bench


def test_run_bench_tiny():
    rec = run_bench("tiny", dp=1, tp=1, sp=1, batch=2, steps=2, warmup=1)
    assert rec["unit"] == "tokens/s" and rec["value"] > 0
    assert rec["step_ms"] > 0  # tflops rounds to 0.00 on CPU-tiny
    assert "noremat" not in rec["metric"]


def test_run_bench_tiny_noremat_tag():
    rec = run_bench("tiny", dp=1, tp=1, sp=1, batch=2, steps=2, warmup=1,
                    remat=False)
    assert rec["metric"].endswith("_noremat")


def test_measure_peak_small():
    """The calibration harness itself (tiny shapes — CPU-runnable)."""
    flops = measure_peak(n=256, iters=2)
    assert flops > 0


def test_run_bench_defaults_are_headline_config():
    """The r6 defaults audit: the zero-flag run IS the measured-winner
    configuration (bf16 moments, saved-exp fused-bwd head, constant
    shift), carries an untagged metric name, full provenance fields,
    and the session canary in session_quality."""
    rec = run_bench("tiny", dp=1, tp=1, sp=1, batch=2, steps=2, warmup=1)
    assert rec["metric"] == "train_tiny_dp1tp1sp1_b2"
    assert rec["optimizer"] == "fused-bf16mom"
    assert rec["head"] == "saved"        # auto-resolved: gate accepts
    assert rec["head_bwd"] == "fused"
    assert rec["softmax_shift"] == 16.0
    assert rec["save_stack"] == "xla"
    assert "canary_gbs" in rec["session_quality"]


def test_run_bench_deviations_tagged():
    """Every deviation from the shipped defaults lands in the metric
    name — cross-round rows stay distinguishable."""
    rec = run_bench("tiny", dp=1, tp=1, sp=1, batch=2, steps=2,
                    warmup=1, optimizer="fused", head="recompute",
                    softmax_shift=None, head_bwd="matmul")
    for tag in ("_opt-fused", "_head-recompute", "_noshift",
                "_hb-matmul"):
        assert tag in rec["metric"], (tag, rec["metric"])


def test_run_bench_pallas_save_stack_reachable():
    """The measured dead-end stays reachable and tagged (the FusedAdam
    -pallas precedent: losers are kept reproducible, not deleted)."""
    rec = run_bench("tiny", dp=1, tp=1, sp=1, batch=2, steps=2,
                    warmup=1, save_stack="pallas")
    assert "_stack-pallas" in rec["metric"]
    assert rec["save_stack"] == "pallas"
    assert rec["value"] > 0
